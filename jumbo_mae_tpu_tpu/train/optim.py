"""Optimizers, schedules, layer-wise LR decay.

Parity targets:

- optimizer set {adamw, lamb(modified), lars, sgd} with the reference's
  hyperparameter wiring (``/root/reference/src/pretraining.py:223-259``,
  ``/root/reference/src/finetuning.py:218-265``);
- modified LAMB: adam scaling → decoupled weight decay → trust ratio applied
  ONLY to weight-decayed (kernel) params (``/root/reference/src/utils.py:124-139``);
- weight-decay mask = parameters literally named "kernel";
- layer-wise LR decay via ``optax.multi_transform`` keyed by encoder depth
  (``/root/reference/src/utils.py:142-147``);
- warmup+cosine schedule (init 1e-6 → peak → end), MAE linear LR scaling
  peak = lr · global_batch/256;
- live LR exposed through ``optax.inject_hyperparams`` for logging.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from functools import partial
from typing import Literal

import optax
from jax.tree_util import tree_map_with_path

OptimizerName = Literal["adamw", "lamb", "lars", "sgd"]
LrScaling = Literal["batch", "none"]


@dataclass(frozen=True)
class OptimConfig:
    name: OptimizerName = "adamw"
    learning_rate: float = 1.5e-4  # base LR (pre-scaling)
    lr_scaling: LrScaling = "batch"
    b1: float = 0.9
    b2: float = 0.95
    eps: float = 1e-8
    weight_decay: float = 0.05
    momentum: float = 0.9
    clip_grad: float = 0.0
    layer_decay: float = 1.0  # <1 enables layer-wise decay
    warmup_steps: int = 0
    training_steps: int = 1
    init_lr: float = 1e-6
    end_lr: float = 1e-5
    # dtype for the Adam first moment (optax mu_dtype). "bfloat16" halves the
    # first-moment HBM traffic in the (bandwidth-bound) optimizer update; the
    # second moment and params stay float32.
    mu_dtype: str | None = None

    def peak_lr(self, global_batch_size: int) -> float:
        if self.lr_scaling == "batch":
            return self.learning_rate * global_batch_size / 256
        return self.learning_rate


def kernel_mask(params):
    """True for every param whose final path key is "kernel"."""
    return tree_map_with_path(lambda kp, _: kp[-1].key == "kernel", params)


def layer_index(path, _unused=None, *, num_layers: int) -> int:
    """Param path → encoder depth for layer-wise LR decay.

    Layout-specific to this framework's trees: the encoder lives under a
    top-level "model" (finetune) with blocks named ``block_i``. embed → 0,
    block_i → i+1, everything else (head, final norm, cls_tokens,
    jumbo_mlp) → num_layers.
    """
    keys = [getattr(k, "key", str(k)) for k in path]
    if keys and keys[0] == "model":
        if len(keys) > 1 and keys[1] == "embed":
            return 0
        if len(keys) > 1 and (m := re.fullmatch(r"block_(\d+)", keys[1])):
            return int(m.group(1)) + 1
    return num_layers


def make_schedule(cfg: OptimConfig, global_batch_size: int) -> optax.Schedule:
    return optax.warmup_cosine_decay_schedule(
        init_value=cfg.init_lr,
        peak_value=cfg.peak_lr(global_batch_size),
        warmup_steps=cfg.warmup_steps,
        decay_steps=cfg.training_steps,
        end_value=cfg.end_lr,
    )


def modified_lamb(
    learning_rate, b1, b2, eps, weight_decay, mask, mu_dtype=None
) -> optax.GradientTransformation:
    """LAMB with the trust ratio restricted to weight-decayed params."""
    return optax.chain(
        optax.scale_by_adam(b1=b1, b2=b2, eps=eps, mu_dtype=mu_dtype),
        optax.add_decayed_weights(weight_decay=weight_decay, mask=mask),
        optax.masked(optax.scale_by_trust_ratio(), mask=mask),
        optax.scale_by_learning_rate(learning_rate),
    )


def make_optimizer(
    cfg: OptimConfig,
    global_batch_size: int,
    *,
    num_layers: int | None = None,
) -> optax.GradientTransformation:
    """Build the full transformation chain, LR exposed in
    ``opt_state.hyperparams["learning_rate"]``."""

    @optax.inject_hyperparams
    def build(learning_rate):
        wd_mask = kernel_mask
        if cfg.name == "adamw":
            tx = optax.adamw(
                learning_rate,
                b1=cfg.b1,
                b2=cfg.b2,
                eps=cfg.eps,
                weight_decay=cfg.weight_decay,
                mask=wd_mask,
                mu_dtype=cfg.mu_dtype,
            )
        elif cfg.name == "lamb":
            tx = modified_lamb(
                learning_rate,
                cfg.b1,
                cfg.b2,
                cfg.eps,
                cfg.weight_decay,
                wd_mask,
                mu_dtype=cfg.mu_dtype,
            )
        elif cfg.name == "lars":
            tx = optax.lars(learning_rate, momentum=cfg.momentum)
        elif cfg.name == "sgd":
            tx = optax.sgd(learning_rate, momentum=cfg.momentum)
        else:
            raise ValueError(f"unknown optimizer {cfg.name!r}")

        if cfg.layer_decay < 1.0:
            if num_layers is None:
                raise ValueError("layer_decay requires num_layers")
            scales = {
                i: optax.scale(cfg.layer_decay ** (num_layers - i))
                for i in range(num_layers + 1)
            }
            label_fn = partial(
                tree_map_with_path, partial(layer_index, num_layers=num_layers)
            )
            tx = optax.chain(tx, optax.multi_transform(scales, label_fn))
        if cfg.clip_grad > 0:
            tx = optax.chain(optax.clip_by_global_norm(cfg.clip_grad), tx)
        return tx

    return build(make_schedule(cfg, global_batch_size))
