"""Deterministic fault injection: a seeded plan of named failure sites.

Long pretraining runs die for reasons the happy path never exercises — a
transient GCS read error, a NaN loss, a wedged checkpoint write, serving
overload. This module makes those failures *first-class inputs*: code
declares named sites (``fault_point("data.shard_open", key=url)``) and a
**fault plan** — parsed from the ``GRAFT_FAULTS`` env var or the
``run.faults`` recipe key — decides, deterministically, which invocations
fail and how. The chaos suite (``tests/test_chaos.py``) drives every
recovery path in the repo through these hooks; production runs pay one
module-global load + ``None`` check per site.

Plan grammar (rules separated by ``;``)::

    rule    = site ':' action [ '(' arg ')' ] [ '@' sel (',' sel)* ]
    action  = 'raise'   [ '(' ExcName ')' ]    -- raise (default OSError)
            | 'delay'   '(' seconds ')'        -- time.sleep
            | 'corrupt' [ '(' nbytes ')' ]     -- flip bytes in the payload
            | 'nan'                            -- replace the value with NaN
    sel     = 'n=' A [ '..' B ]   -- rule-local invocation index (0-based,
                                     inclusive range)
                                     -- counting selectors index into the
                                     rule's *filtered* stream: invocations
                                     rejected by 'key~'/'host=' don't
                                     advance n, so 'key~r1,n<1' is exactly
                                     "r1's first call"
            | 'n<' N              -- first N invocations
            | 'n%' K '=' R        -- every K-th invocation with remainder R
            | 'p=' F              -- seeded Bernoulli(F) per invocation
            | 'key~' SUBSTR       -- only when the site key contains SUBSTR
            | 'host=' I           -- only on process/host index I of a
                                     multi-process run (fleet chaos)
    seed    = 'seed=' N           -- standalone rule: seeds every 'p=' draw

All selectors of a rule must match for it to fire. Examples::

    data.shard_open:raise(OSError)@n<2            # first two opens fail
    train.loss:nan@n=4..6                         # NaN loss at calls 4-6
    data.shard_open:raise@key~shard-0003          # one shard always fails
    serve.submit:delay(0.05)@n%10=0               # every 10th submit is slow
    seed=7;data.decode:corrupt(4)@p=0.01          # 1% of decodes corrupted
    serve.replica:raise(RuntimeError)@key~r1,n<1  # crash replica r1's first batch
    serve.replica:delay(5.0)@key~r2               # wedge replica r2 (hang path)
    serve.preempt:raise@n=1                       # preempt (drain) one replica
    ckpt.load:corrupt(4)                          # diverge a hot-swap restore
    data.decode:delay(0.2)@host=1                 # straggle host 1 of a pod
    host.leak:corrupt(8)                          # leak 8 MB/step on the host
    batch.worker:raise@n<1                        # kill a batch-job worker mid-shard
    fleet.wedge:delay(30)@host=1,n<1              # wedge host 1's step (hangwatch)

The ``host=`` selector resolves the current process's host index lazily at
fire time: an explicit :func:`set_host_index` (``cli/train.py`` pins it
right after distributed init, and exports it via ``GRAFT_HOST`` so data
worker subprocesses inherit the identity), else the ``GRAFT_HOST`` env var,
else ``jax.process_index()`` when jax is already imported, else 0.

Known sites (free-form names are allowed; these are the wired ones):
``data.shard_open``, ``data.decode``, ``train.loss``, ``train.grad``,
``serve.submit``, ``serve.replica``, ``serve.preempt``, ``ckpt.save``,
``ckpt.load``, ``host.leak``, ``batch.worker``, ``publish.export``,
``fleet.wedge``.

``serve.replica`` fires at the top of each replica's batched predict with
``key`` = the replica name (``r0``, ``r1``, …), so ``key~`` targets one
replica: ``raise`` is a crash, ``delay`` past the supervisor's hang timeout
is a hang. ``ckpt.load`` fires on the weight-swap restore path with the
restored params tree as ``data`` — ``corrupt(k)`` sign-flips ``k``
deterministically-chosen leaves so the parity gate sees a diverged model
(a real bad-push, not a parse error), while ``raise`` models an unreadable
checkpoint. ``serve.preempt`` is ticked by the :class:`ReplicaSet` supervisor once per
tick per routable replica (``key`` = replica name): a ``raise`` firing is a
preemption notice — the replica *drains* (pause → idle → retire → restart)
instead of dying with its queue, the graceful twin of ``serve.replica``'s
crash. ``batch.worker`` fires in the offline batch runner's worker loop
(``key`` = worker name): a ``raise`` kills that worker dead without
releasing its shard lease — the lease-expiry/steal path another worker must
recover. ``host.leak`` is the memory-observability chaos site, ticked
once per train step: ``corrupt(n)`` retains ``n`` MB in a module-level
ballast list each time it fires (a controllable host leak the
``LeakSentinel`` must catch and attribute), ``raise`` clears the ballast
(the "leak fixed" edge); :func:`leak_ballast_bytes` is the accounting
probe `obs/memwatch.py` registers so the attribution is testable.
``publish.export`` fires in the weights publisher's export
(``serve/publisher.py``) with the payload bytes as ``data``, *after* the
manifest's digests are sealed: ``corrupt(k)`` ships a poisoned artifact
the watcher's manifest verification must quarantine, ``raise`` models a
torn export (nothing commits — the atomic-rename contract under test).
``fleet.wedge`` is the elastic-training hang site, ticked once per train
step on the dispatch path (``key`` = step, OUTSIDE any hangwatch
``expected()`` window): ``delay(s)`` past ``run.hangwatch_deadline_s``
holds that host's step so the survivors block in the next collective —
the wedged-all-reduce failure the hang watchdog must convert into an
``EXIT_HANG`` death the :class:`ElasticSupervisor` restarts.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field

from jumbo_mae_tpu_tpu.obs.metrics import get_registry

# Exception classes `raise(Name)` may name — a closed set, so a fault plan
# can never be used to execute arbitrary attribute lookups.
_EXCEPTIONS = {
    "OSError": OSError,
    "IOError": OSError,
    "ConnectionError": ConnectionError,
    "TimeoutError": TimeoutError,
    "RuntimeError": RuntimeError,
    "ValueError": ValueError,
    "MemoryError": MemoryError,
}

_ACTIONS = ("raise", "delay", "corrupt", "nan")

# Every wired ``fault_point(...)`` site. Free-form names still work at
# runtime, but plans naming a site outside this tuple can never fire —
# ``tools.graftlint`` CON003 cross-checks plan strings (tests, CI, README
# cookbook) and call sites against it, so typos surface statically.
KNOWN_SITES = (
    "data.shard_open",
    "data.decode",
    "train.loss",
    "train.grad",
    "serve.submit",
    "serve.replica",
    "serve.preempt",
    "ckpt.save",
    "ckpt.load",
    "host.leak",
    "batch.worker",
    "publish.export",
    "fleet.wedge",
)


class FaultInjected(RuntimeError):
    """Default marker mixin-free exception is OSError; this name is only
    used in reprs/logs when a rule raises without naming a class."""


@dataclass
class FaultRule:
    site: str
    action: str
    arg: str | float | None = None
    selectors: list[tuple[str, object]] = field(default_factory=list)
    calls: int = 0  # invocations that passed this rule's identity filters
    hits: int = 0   # invocations this rule actually fired on

    def filter_matches(self, key: str | None) -> bool:
        """Identity selectors (``key~``, ``host=``): does this invocation
        belong to the stream the rule targets at all? Invocations that fail
        here are invisible to the rule — they do not advance ``calls`` — so
        ``key~r1,n<1`` means "r1's first call", not "the first call overall,
        if it happens to be r1's" (which would race against other keys)."""
        for kind, val in self.selectors:
            if kind == "key~":
                if key is None or val not in str(key):
                    return False
            elif kind == "host=":
                if current_host_index() != val:
                    return False
        return True

    def gate_matches(self, rng) -> bool:
        """Counting selectors (``n=``/``n<``/``n%``/``p=``), evaluated
        against the filtered invocation index."""
        n = self.calls
        for kind, val in self.selectors:
            if kind == "n=":
                lo, hi = val
                if not (lo <= n <= hi):
                    return False
            elif kind == "n<":
                if not n < val:
                    return False
            elif kind == "n%":
                k, r = val
                if n % k != r:
                    return False
            elif kind == "p=":
                # one seeded draw per filtered invocation
                if rng.random() >= val:
                    return False
        return True


def _parse_selector(text: str) -> tuple[str, object]:
    text = text.strip()
    if text.startswith("key~"):
        return ("key~", text[len("key~"):])
    if text.startswith("p="):
        p = float(text[2:])
        if not 0.0 <= p <= 1.0:
            raise ValueError(f"p= selector must be in [0,1], got {text!r}")
        return ("p=", p)
    if text.startswith("n<"):
        return ("n<", int(text[2:]))
    if text.startswith("n%"):
        mod, _, rem = text[2:].partition("=")
        if not rem:
            raise ValueError(f"n%% selector needs K=R, got {text!r}")
        return ("n%", (int(mod), int(rem)))
    if text.startswith("n="):
        lo, sep, hi = text[2:].partition("..")
        return ("n=", (int(lo), int(hi) if sep else int(lo)))
    if text.startswith("host="):
        return ("host=", int(text[len("host="):]))
    raise ValueError(f"unknown fault selector {text!r}")


def _parse_rule(text: str) -> FaultRule:
    head, _, sel = text.partition("@")
    site, colon, act = head.partition(":")
    if not colon or not site.strip():
        raise ValueError(f"fault rule needs site:action, got {text!r}")
    act = act.strip()
    arg: str | float | None = None
    if "(" in act:
        if not act.endswith(")"):
            raise ValueError(f"unbalanced '(' in fault action {act!r}")
        act, _, raw = act[:-1].partition("(")
        arg = raw.strip()
    if act not in _ACTIONS:
        raise ValueError(f"unknown fault action {act!r} (one of {_ACTIONS})")
    if act == "delay":
        arg = float(arg) if arg else 0.01
    elif act == "corrupt":
        arg = int(arg) if arg else 8
    elif act == "raise" and arg and arg not in _EXCEPTIONS:
        raise ValueError(
            f"raise({arg}) not allowed; choose from {sorted(_EXCEPTIONS)}"
        )
    selectors = [_parse_selector(s) for s in sel.split(",") if s.strip()] if sel else []
    return FaultRule(site=site.strip(), action=act, arg=arg, selectors=selectors)


class FaultPlan:
    """A parsed set of rules, grouped by site, with deterministic firing.

    All mutable state (per-rule counters, the Bernoulli stream) is guarded
    by one lock — sites like ``serve.submit`` fire from many threads.
    """

    def __init__(self, rules: list[FaultRule], *, seed: int = 0, text: str = ""):
        import random

        self.text = text
        self.seed = seed
        self._by_site: dict[str, list[FaultRule]] = {}
        for r in rules:
            self._by_site.setdefault(r.site, []).append(r)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        reg = get_registry()
        self._m_injected = reg.counter(
            "faults_injected_total",
            "faults fired by the active injection plan",
            labels=("site", "action"),
        )

    @classmethod
    def parse(cls, text: str) -> "FaultPlan":
        seed = 0
        rules = []
        for part in text.split(";"):
            part = part.strip()
            if not part:
                continue
            if part.startswith("seed="):
                seed = int(part[len("seed="):])
                continue
            rules.append(_parse_rule(part))
        return cls(rules, seed=seed, text=text)

    def sites(self) -> list[str]:
        return sorted(self._by_site)

    def counts(self) -> dict[str, tuple[int, int]]:
        """{'site:action' → (calls, hits)} — test/debug readout."""
        with self._lock:
            return {
                f"{r.site}:{r.action}": (r.calls, r.hits)
                for rs in self._by_site.values()
                for r in rs
            }

    def fire(self, site: str, key: str | None, data):
        """Apply the first matching rule for ``site``; returns the (possibly
        replaced) ``data``. Raise/delay actions happen here."""
        rules = self._by_site.get(site)
        if not rules:
            return data
        with self._lock:
            fired = None
            for r in rules:
                # identity filters gate the counter too: a rule only "sees"
                # invocations from its own key/host stream, so n-selectors
                # index into that stream deterministically regardless of how
                # other keys interleave with it
                if not r.filter_matches(key):
                    continue
                if fired is None and r.gate_matches(self._rng):
                    fired = r
                    r.hits += 1
                r.calls += 1
            if fired is None:
                return data
            self._m_injected.labels(site, fired.action).inc()
        # side effects OUTSIDE the lock — a delay must not serialize other
        # sites, and a raised exception must not poison the lock
        if fired.action == "raise":
            exc = _EXCEPTIONS.get(str(fired.arg) or "", OSError)
            raise exc(
                f"fault injected at {site} (rule {fired.site}:{fired.action}"
                f"{f'({fired.arg})' if fired.arg else ''})"
            )
        if fired.action == "delay":
            time.sleep(float(fired.arg))
            return data
        if fired.action == "corrupt":
            if data is _LEAK_TOKEN:
                # host.leak semantics: corrupt(n) has nothing to corrupt —
                # it RETAINS n MB per firing in the module ballast, the
                # controllable host leak the LeakSentinel must attribute
                _LEAK_BALLAST.append(bytearray(int(fired.arg) * 1024 * 1024))
                return data
            return _corrupt_bytes(data, int(fired.arg), self.seed, fired.hits)
        if fired.action == "nan":
            return float("nan")
        return data  # pragma: no cover - _ACTIONS is closed


def _corrupt_bytes(data, nbytes: int, seed: int, salt: int):
    """Corrupt a payload deterministically. Bytes payloads (tar members,
    image blobs) get ``nbytes`` flipped bytes; dict payloads (a restored
    params tree at ``ckpt.load``) get ``nbytes`` leaves sign-flipped and
    rescaled — numerically plausible, parity-detectably wrong. Anything
    else is returned untouched."""
    import random

    if isinstance(data, (bytes, bytearray)):
        if len(data) == 0:
            return data
        rng = random.Random((seed, salt, len(data)))
        buf = bytearray(data)
        for _ in range(min(nbytes, len(buf))):
            i = rng.randrange(len(buf))
            buf[i] ^= 0xFF
        return bytes(buf)
    if isinstance(data, dict) and data:
        import numpy as np
        from jax import tree_util

        leaves, treedef = tree_util.tree_flatten(data)
        idx = [
            i
            for i, leaf in enumerate(leaves)
            if hasattr(leaf, "shape") and getattr(leaf, "size", 0)
        ]
        if not idx:
            return data
        rng = random.Random((seed, salt, len(idx)))
        chosen = rng.sample(idx, min(nbytes, len(idx)))
        out = list(leaves)
        for i in chosen:
            arr = np.asarray(out[i])
            out[i] = (-3.0 * arr - 0.5).astype(arr.dtype)
        return tree_util.tree_unflatten(treedef, out)
    return data


# ------------------------------------------------------------ host ballast

# The host.leak site's retained memory: every corrupt(n) firing appends an
# n-MB buffer here; a raise firing clears it. Module-level on purpose —
# a leak that vanished with its injector would be unmeasurable.
_LEAK_BALLAST: list[bytearray] = []
_LEAK_TOKEN = object()  # sentinel payload marking a host.leak tick


def leak_ballast_bytes() -> int:
    """Current bytes retained by the ``host.leak`` site — the accounting
    probe ``obs/memwatch.py`` registers as the ``fault_ballast`` component
    so the leak sentinel's attribution is chaos-testable."""
    return sum(len(b) for b in _LEAK_BALLAST)


def host_leak_tick(key: str | None = None) -> int:
    """Tick the ``host.leak`` chaos site (once per train step).

    ``corrupt(n)`` rules grow the module ballast by n MB per firing;
    ``raise`` rules clear it (the fault's exception never propagates — a
    *memory* fault must not crash the step loop). Returns the current
    ballast size so the call site can assert/log it.
    """
    try:
        fault_point("host.leak", key=key, data=_LEAK_TOKEN)
    except Exception:  # noqa: BLE001 - raise action = "leak fixed", clear
        _LEAK_BALLAST.clear()
    return leak_ballast_bytes()


# ------------------------------------------------------------ host identity

_HOST_INDEX: int | None = None
_HOST_ENV = "GRAFT_HOST"


def set_host_index(index: int | None) -> None:
    """Pin this process's host index for ``@host=`` selectors and mirror it
    into the ``GRAFT_HOST`` env var so data-worker subprocesses (which
    activate the same plan via ``GRAFT_FAULTS``) inherit the identity.
    ``None`` resets to lazy resolution (tests)."""
    global _HOST_INDEX
    if index is None:
        _HOST_INDEX = None
        os.environ.pop(_HOST_ENV, None)
    else:
        _HOST_INDEX = int(index)
        os.environ[_HOST_ENV] = str(_HOST_INDEX)


def current_host_index() -> int:
    """The host index ``@host=`` compares against. Resolution order:
    :func:`set_host_index` > ``GRAFT_HOST`` env > ``jax.process_index()``
    when jax is already imported (this layer never imports it) > 0. The
    resolved value is cached; the bare-0 fallback is not, since distributed
    init may simply not have happened yet."""
    global _HOST_INDEX
    if _HOST_INDEX is not None:
        return _HOST_INDEX
    env = os.environ.get(_HOST_ENV)
    if env is not None:
        try:
            _HOST_INDEX = int(env)
            return _HOST_INDEX
        except ValueError:
            pass
    import sys

    if "jax" in sys.modules:
        try:
            _HOST_INDEX = int(sys.modules["jax"].process_index())
            return _HOST_INDEX
        except Exception:  # noqa: BLE001 - backend not initialized yet
            pass
    return 0


# ---------------------------------------------------------------- installers

_PLAN: FaultPlan | None = None
_ENV_VAR = "GRAFT_FAULTS"


def install_plan(spec: "str | FaultPlan | None") -> FaultPlan | None:
    """Activate a fault plan process-wide (a string is parsed first).
    ``None``/empty deactivates. Returns the active plan."""
    global _PLAN
    if spec is None or spec == "":
        _PLAN = None
        _LEAK_BALLAST.clear()  # deactivation heals the injected leak
        return None
    plan = FaultPlan.parse(spec) if isinstance(spec, str) else spec
    _PLAN = plan
    return plan


def clear_plan() -> None:
    install_plan(None)


def active_plan() -> FaultPlan | None:
    return _PLAN


def faults_active() -> bool:
    return _PLAN is not None


def fault_point(site: str, *, key: str | None = None, data=None):
    """Declare a failure site. With no active plan this is a global load and
    a branch — the zero-overhead contract production runs rely on. With a
    plan, the first matching rule fires: ``raise``/``delay`` happen here;
    ``corrupt``/``nan`` transform and return ``data``."""
    plan = _PLAN
    if plan is None:
        return data
    return plan.fire(site, key, data)


# env activation: a set GRAFT_FAULTS makes every entry point (and every data
# worker subprocess, which inherits the parent env) chaos-enabled at import
if os.environ.get(_ENV_VAR):
    install_plan(os.environ[_ENV_VAR])
