"""Resilience layer: deterministic fault injection + divergence sentinel.

- ``faults.inject``   — named fault sites driven by a seeded plan
  (``GRAFT_FAULTS`` env / ``run.faults`` recipe key); no-op when unset.
- ``faults.sentinel`` — on-device non-finite step guard and the host-side
  divergence sentinel (skip / EMA spike / rollback policy).
"""

from jumbo_mae_tpu_tpu.faults.inject import (
    FaultPlan,
    FaultRule,
    active_plan,
    clear_plan,
    current_host_index,
    fault_point,
    faults_active,
    host_leak_tick,
    install_plan,
    leak_ballast_bytes,
    set_host_index,
)
from jumbo_mae_tpu_tpu.faults.sentinel import (
    DivergenceError,
    DivergenceSentinel,
    SentinelConfig,
    guarded_apply_gradients,
)

__all__ = [
    "DivergenceError",
    "DivergenceSentinel",
    "FaultPlan",
    "FaultRule",
    "SentinelConfig",
    "active_plan",
    "clear_plan",
    "current_host_index",
    "fault_point",
    "faults_active",
    "guarded_apply_gradients",
    "host_leak_tick",
    "install_plan",
    "leak_ballast_bytes",
    "set_host_index",
]
