"""Training divergence sentinel: skip bad steps on device, roll back on runs.

Two cooperating halves:

- **Device guard** (:func:`guarded_apply_gradients`, compiled into the train
  step by ``make_train_step(guard_nonfinite=True)``): an all-reduced
  ``isfinite(loss) & isfinite(grad_norm)`` flag — the mean over the
  globally-sharded batch IS the cross-replica value under GSPMD, so no
  explicit collective is needed — gates the optimizer update through
  ``lax.cond``. A non-finite step passes the state through untouched
  (params, opt state, BatchNorm stats) except the step counter, which still
  advances so the data stream and LR schedule stay aligned. Both branches
  have identical structure: **no recompile**, ever.

- **Host sentinel** (:class:`DivergenceSentinel`, driven by ``cli/train.py``
  at log boundaries — per-step host sync would serialize dispatch against
  device compute): counts consecutive bad steps (device-skipped or
  EMA-spike), and after ``patience`` of them in a row asks for a rollback to
  the last ``last/`` checkpoint (data cursor included). Skips, spikes and
  rollbacks are counted in the obs registry (``train_steps_skipped_total``,
  ``train_loss_spikes_total``, ``train_rollbacks_total``).

Why both: skipping protects the state from a *transient* bad batch; rollback
recovers from *persistent* badness (params already diverged, poisoned data
region) that skipping can't fix because the state itself is the problem.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import optax

from jumbo_mae_tpu_tpu.obs.metrics import get_registry


def guarded_apply_gradients(state, grads, loss):
    """Optimizer update gated on finiteness, inside the jitted step.

    Returns ``(new_state, grad_norm, finite)``; on a non-finite ``loss`` or
    ``grad_norm`` the update (and any BatchNorm-stats replace the caller does
    afterwards) must be skipped — the state comes back unchanged except
    ``step + 1``.
    """
    grad_norm = optax.global_norm(grads)
    finite = jnp.isfinite(loss) & jnp.isfinite(grad_norm)

    def _update(_):
        return state.apply_gradients(grads=grads)

    def _skip(_):
        return state.replace(step=state.step + 1)

    new_state = jax.lax.cond(finite, _update, _skip, operand=None)
    return new_state, grad_norm, finite


@dataclass(frozen=True)
class SentinelConfig:
    """Host-side divergence policy (RunConfig's ``sentinel_*`` knobs)."""

    patience: int = 3           # consecutive bad steps before rollback
    spike_factor: float = 10.0  # loss > factor x EMA counts as a bad step
    ema_beta: float = 0.98      # loss EMA decay
    max_rollbacks: int = 3      # give up (raise) after this many rollbacks


class DivergenceError(RuntimeError):
    """Raised when training diverges beyond what the sentinel can repair
    (no checkpoint to roll back to, or ``max_rollbacks`` exhausted)."""


class DivergenceSentinel:
    """Streaming bad-step detector fed with per-step host metrics.

    ``observe(step, metrics)`` is called once per fetched train step, in step
    order; it returns ``True`` when the consecutive-bad streak has reached
    ``patience`` and the caller should roll back. The EMA and streak reset
    after a rollback (``record_rollback``) — the restored stream re-earns its
    baseline.

    ``on_event`` (settable anytime) is the diagnostics tap: a callable
    ``(kind, payload_dict)`` invoked on every ``bad_step`` / ``loss_spike``
    / ``rollback`` verdict with the exact step index — the run journal and
    flight recorder subscribe here, so a rollback is *explainable* offline,
    not just counted. A raising callback is swallowed: diagnostics must
    never take down the recovery path they observe.
    """

    def __init__(self, cfg: SentinelConfig, registry=None, on_event=None):
        self.cfg = cfg
        self.on_event = on_event
        reg = registry if registry is not None else get_registry()
        self._m_skipped = reg.counter(
            "train_steps_skipped_total",
            "optimizer updates skipped on a non-finite loss/grad",
        )
        self._m_spikes = reg.counter(
            "train_loss_spikes_total",
            "steps whose loss exceeded spike_factor x EMA",
        )
        self._m_rollbacks = reg.counter(
            "train_rollbacks_total",
            "automatic rollbacks to the last checkpoint",
        )
        self.bad_streak = 0
        self.rollbacks = 0
        self.ema: float | None = None

    def _notify(self, kind: str, **payload) -> None:
        cb = self.on_event
        if cb is None:
            return
        try:
            cb(kind, payload)
        except Exception:  # noqa: BLE001 - diagnostics never break recovery
            pass

    def observe(self, step: int, metrics: dict) -> bool:
        """Digest one step's host-fetched metrics; True → roll back now."""
        skipped = float(metrics.get("skipped", 0.0)) >= 0.5
        loss = float(metrics.get("loss", math.nan))
        if skipped or not math.isfinite(loss):
            self._m_skipped.inc()
            self.bad_streak += 1
            self._notify(
                "bad_step",
                step=step,
                loss=loss,
                reason="device_skip" if skipped else "nonfinite_loss",
                streak=self.bad_streak,
            )
            return self.bad_streak >= self.cfg.patience
        if (
            self.ema is not None
            and self.cfg.spike_factor > 0
            and loss > self.cfg.spike_factor * max(self.ema, 1e-12)
        ):
            self._m_spikes.inc()
            self.bad_streak += 1
            self._notify(
                "loss_spike",
                step=step,
                loss=loss,
                ema=self.ema,
                streak=self.bad_streak,
            )
            # a spike still carries signal — let the EMA drift toward it so
            # a legitimate regime change stops counting as bad eventually
            self._update_ema(loss)
            return self.bad_streak >= self.cfg.patience
        self.bad_streak = 0
        self._update_ema(loss)
        return False

    def _update_ema(self, loss: float) -> None:
        b = self.cfg.ema_beta
        self.ema = loss if self.ema is None else b * self.ema + (1 - b) * loss

    def record_rollback(self) -> None:
        """Count a performed rollback and reset the streak/EMA baselines;
        raises :class:`DivergenceError` once the budget is exhausted."""
        self.rollbacks += 1
        self._m_rollbacks.inc()
        self.bad_streak = 0
        self.ema = None
        self._notify(
            "rollback",
            rollbacks=self.rollbacks,
            max_rollbacks=self.cfg.max_rollbacks,
        )
        if self.rollbacks > self.cfg.max_rollbacks:
            raise DivergenceError(
                f"training diverged {self.rollbacks} times "
                f"(sentinel_max_rollbacks={self.cfg.max_rollbacks}) — "
                "rollback is not converging; inspect the data/LR schedule"
            )
