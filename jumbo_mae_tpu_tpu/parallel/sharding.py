"""Sharding rules: how arrays are laid out over the mesh.

FSDP (ZeRO-3-style) parameter sharding is a *rule*, not a hand-written table:
every array in the train state gets its largest axis divisible by the ``fsdp``
axis size sharded, provided the array is big enough to be worth scattering
(``min_shard_size``). Scalars, norms, biases and other small tensors stay
replicated. Optimizer moments follow their parameters automatically because
the rule is applied to the whole state pytree by shape.

The batch is sharded over (data, fsdp) on its leading axis, so the product of
both axes is the total data-parallel degree — fsdp devices see distinct
micro-batches AND hold distinct parameter shards; GSPMD turns the gradient
all-reduce into reduce-scatter + all-gather exactly like hand-written ZeRO.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_param_spec(
    shape: tuple[int, ...],
    mesh: Mesh,
    *,
    axis: str = "fsdp",
    min_shard_size: int = 2**16,
) -> P:
    """Choose a PartitionSpec for one array: shard the largest divisible dim
    on ``axis``, or replicate if too small / nothing divides."""
    size = mesh.shape[axis]
    if size <= 1 or int(np.prod(shape)) < min_shard_size:
        return P()
    candidates = [i for i, d in enumerate(shape) if d % size == 0]
    if not candidates:
        return P()
    dim = max(candidates, key=lambda i: shape[i])
    spec = [None] * len(shape)
    spec[dim] = axis
    return P(*spec)


def infer_state_sharding(
    state_shapes: Any,
    mesh: Mesh,
    *,
    axis: str = "fsdp",
    min_shard_size: int = 2**16,
) -> Any:
    """Map a pytree of ShapeDtypeStructs (from ``jax.eval_shape``) to
    NamedShardings using :func:`shard_param_spec` per leaf."""

    def leaf_sharding(leaf):
        shape = getattr(leaf, "shape", ())
        return NamedSharding(
            mesh,
            shard_param_spec(
                tuple(shape), mesh, axis=axis, min_shard_size=min_shard_size
            ),
        )

    return jax.tree_util.tree_map(leaf_sharding, state_shapes)


def batch_sharding(
    mesh: Mesh, *, accum: bool = False, leading_axes=("data", "fsdp")
) -> NamedSharding:
    """Shard the leading (batch) dim over the data-parallel axes. With
    ``accum=True`` the batch is (accum, micro, ...): dim 0 stays replicated
    and dim 1 (micro batch) is sharded."""
    axes = tuple(a for a in leading_axes if mesh.shape[a] > 1) or None
    return NamedSharding(mesh, P(None, axes) if accum else P(axes))
