"""Sharding rules: how arrays are laid out over the mesh.

Two rule families compose per array, in priority order:

**Tensor parallel (Megatron-style), ``tensor`` axis.** Matched by parameter
*path* — the contraction structure of each layer decides which dim shards:

- attention ``q/k/v`` kernels ``(dim, heads, head_dim)`` shard the *heads*
  dim (and their ``(heads, head_dim)`` biases likewise), so every device
  computes a disjoint subset of heads;
- the attention ``out`` kernel ``(heads, head_dim, dim)`` shards heads on
  input — its matmul contracts the sharded dim, which is what makes GSPMD
  emit the single per-block all-reduce of Megatron TP;
- MLP ``fc1`` ``(dim, hidden)`` shards *hidden* on output (bias too),
  ``fc2`` ``(hidden, dim)`` shards *hidden* on input — same
  column-then-row-parallel pairing.

**FSDP (ZeRO-3-style), ``fsdp`` axis.** A *shape* rule: the largest
still-unsharded axis divisible by the ``fsdp`` size is scattered, provided
the array is big enough to be worth it (``min_shard_size``). Scalars, norms
and other small tensors stay replicated.

Optimizer moments follow their parameters automatically because the rules
are applied to the whole train-state pytree and matched on the *trailing*
path components (``.../attn/q/kernel`` matches inside ``opt_state...mu`` the
same way it matches inside ``params``).

The batch is sharded over (data, fsdp) on its leading axis, so the product of
both axes is the total data-parallel degree — fsdp devices see distinct
micro-batches AND hold distinct parameter shards; GSPMD turns the gradient
all-reduce into reduce-scatter + all-gather exactly like hand-written ZeRO.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Modules whose parameters carry a tensor-parallel dim, keyed by the
# (parent-module, leaf) tail of the parameter path. Values: which dim of the
# kernel/bias shards. q/k/v kernels are (dim, heads, head_dim) DenseGeneral
# kernels; fc kernels are plain (in, out) Dense kernels.
_TP_KERNEL_DIM = {"q": 1, "k": 1, "v": 1, "out": 0, "fc1": 1, "fc2": 0}
# Biases shard only where the *output* of the matmul is sharded (column
# parallel): q/k/v bias (heads, head_dim) dim 0, fc1 bias (hidden,) dim 0.
_TP_BIAS_DIM = {"q": 0, "k": 0, "v": 0, "fc1": 0}


def _path_names(path) -> list[str]:
    out = []
    for k in path:
        name = getattr(k, "key", None)
        if name is None:
            name = getattr(k, "name", None)
        out.append(str(name) if name is not None else str(k))
    return out


def tensor_dim(path, shape: tuple[int, ...], tp_size: int) -> int | None:
    """Which dim of this parameter shards over the ``tensor`` axis, or None.

    Matched on the trailing (module, leaf) path names so the rule applies
    identically under ``params`` and under optimizer-moment subtrees.
    """
    if tp_size <= 1 or len(path) < 2:
        return None
    names = _path_names(path[-2:])
    parent, leaf = names[0], names[1]
    table = (
        _TP_KERNEL_DIM
        if leaf == "kernel"
        else _TP_BIAS_DIM
        if leaf == "bias"
        else None
    )
    if table is None or parent not in table:
        return None
    dim = table[parent]
    if dim >= len(shape) or shape[dim] % tp_size:
        return None
    return dim


def shard_param_spec(
    shape: tuple[int, ...],
    mesh: Mesh,
    *,
    axis: str = "fsdp",
    min_shard_size: int = 2**16,
    path=(),
    tensor_axis: str = "tensor",
) -> P:
    """Compose the TP rule (path-based) with the FSDP rule (shape-based)."""
    spec: list = [None] * len(shape)

    tp_size = mesh.shape.get(tensor_axis, 1)
    tp_dim = tensor_dim(path, shape, tp_size)
    if tp_dim is not None:
        spec[tp_dim] = tensor_axis

    size = mesh.shape.get(axis, 1)  # e.g. ("data","pipe") pipeline meshes
    if size > 1 and int(np.prod(shape)) >= min_shard_size:
        candidates = [
            i
            for i, d in enumerate(shape)
            if spec[i] is None and d % size == 0
        ]
        if candidates:
            dim = max(candidates, key=lambda i: shape[i])
            spec[dim] = axis

    return P(*spec) if any(s is not None for s in spec) else P()


def infer_state_sharding(
    state_shapes: Any,
    mesh: Mesh,
    *,
    axis: str = "fsdp",
    min_shard_size: int = 2**16,
) -> Any:
    """Map a pytree of ShapeDtypeStructs (from ``jax.eval_shape``) to
    NamedShardings using :func:`shard_param_spec` per leaf."""

    def leaf_sharding(path, leaf):
        shape = getattr(leaf, "shape", ())
        return NamedSharding(
            mesh,
            shard_param_spec(
                tuple(shape),
                mesh,
                axis=axis,
                min_shard_size=min_shard_size,
                path=path,
            ),
        )

    return jax.tree_util.tree_map_with_path(leaf_sharding, state_shapes)


def batch_sharding(
    mesh: Mesh, *, accum: bool = False, leading_axes=("data", "fsdp")
) -> NamedSharding:
    """Shard the leading (batch) dim over the data-parallel axes. With
    ``accum=True`` the batch is (accum, micro, ...): dim 0 stays replicated
    and dim 1 (micro batch) is sharded."""
    axes = tuple(a for a in leading_axes if mesh.shape.get(a, 1) > 1) or None
    return NamedSharding(mesh, P(None, axes) if accum else P(axes))
