from jumbo_mae_tpu_tpu.parallel.mesh import MeshConfig, create_mesh
from jumbo_mae_tpu_tpu.parallel.pipeline import (
    create_pipeline_mesh,
    make_plain_pipeline_apply,
    gpipe,
    pipelined_blocks_apply,
    pipelined_jumbo_blocks_apply,
    stack_block_params,
    unstack_block_params,
)
from jumbo_mae_tpu_tpu.parallel.ring_attention import (
    ring_attention,
    ring_attention_sharded,
    ring_self_attention,
)
from jumbo_mae_tpu_tpu.parallel.sharding import (
    batch_sharding,
    infer_state_sharding,
    shard_param_spec,
)

__all__ = [
    "MeshConfig",
    "create_mesh",
    "create_pipeline_mesh",
    "make_plain_pipeline_apply",
    "gpipe",
    "pipelined_blocks_apply",
    "pipelined_jumbo_blocks_apply",
    "stack_block_params",
    "unstack_block_params",
    "batch_sharding",
    "infer_state_sharding",
    "ring_attention",
    "ring_attention_sharded",
    "ring_self_attention",
    "shard_param_spec",
]

