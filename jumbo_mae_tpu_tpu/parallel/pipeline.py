"""GPipe-style pipeline parallelism over a ``pipe`` mesh axis.

The reference has no pipeline parallelism (SURVEY §2.10: PP — NO); its
ViT sizes fit one chip. This module adds it as a first-class runtime
capability for depth-sharding larger stacks: transformer blocks are
stacked along a leading "stage" axis and sharded over the ``pipe`` mesh
axis — each device owns ``layers / n_stages`` consecutive blocks — and
microbatches stream through the classic GPipe schedule:

- tick t: stage 0 feeds microbatch t (clamped past the last one), every
  stage applies its local blocks, activations hop to the next stage with
  ``lax.ppermute`` (one ICI neighbor hop per tick — the mesh should place
  ``pipe`` on ICI);
- after ``microbatches + n_stages − 1`` ticks the last stage has collected
  every microbatch; a masked ``psum`` replicates the output.

Everything is ``lax.scan``/``ppermute`` inside one ``shard_map`` — a
single XLA program, fully differentiable (``ppermute`` transposes to the
reverse hop, so ``jax.grad`` yields the backward pipeline schedule
automatically). Composes with data parallelism by sharding the microbatch
batch dim over ``data`` in the same ``shard_map``.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp

from jumbo_mae_tpu_tpu.utils import compat
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P


def create_pipeline_mesh(
    data: int, pipe: int, devices: list | None = None
) -> Mesh:
    """(data, pipe) mesh: consecutive devices form a pipeline (ppermute
    hops ride neighbor ICI links), replicated ``data`` ways."""
    devices = devices if devices is not None else jax.devices()
    if data * pipe > len(devices):
        raise ValueError(
            f"mesh (data={data}, pipe={pipe}) needs {data * pipe} devices, "
            f"have {len(devices)}"
        )
    dev = np.array(devices[: data * pipe]).reshape(data, pipe)
    return Mesh(dev, ("data", "pipe"))


def stack_block_params(params: dict, prefix: str = "block_") -> tuple[dict, int]:
    """Stack homogeneous per-block subtrees (``block_0`` … ``block_{L-1}``,
    the JumboViT/MAE-decoder layout) into one tree with a leading block
    axis — the form :func:`gpipe` shards over ``pipe``."""
    names = sorted(
        (k for k in params if k.startswith(prefix)),
        key=lambda k: int(k[len(prefix) :]),
    )
    if not names:
        raise ValueError(f"no {prefix}* subtrees in params")
    stacked = jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *[params[n] for n in names]
    )
    return stacked, len(names)


def unstack_block_params(stacked: dict, prefix: str = "block_") -> dict:
    """Inverse of :func:`stack_block_params`."""
    n = jax.tree_util.tree_leaves(stacked)[0].shape[0]
    return {
        f"{prefix}{i}": jax.tree_util.tree_map(lambda x, i=i: x[i], stacked)
        for i in range(n)
    }


def gpipe(
    block_fn: Callable[..., jax.Array],
    stacked_params: dict,
    x: jax.Array,
    *,
    mesh: Mesh,
    microbatches: int,
    axis: str = "pipe",
    data_axis: str | None = "data",
    shared_params: dict | None = None,
    rng: jax.Array | None = None,
) -> jax.Array:
    """Run ``x`` through all stacked blocks under the GPipe schedule.

    ``block_fn(one_block_params, h) -> h`` must be pure (e.g. a flax
    ``apply`` with ``deterministic=True``). ``stacked_params`` carries the
    leading block axis (from :func:`stack_block_params`); the block count
    must divide by the mesh's ``pipe`` size. ``x`` is the global batch;
    ``microbatches`` must divide it. Returns the full-batch output,
    replicated over ``pipe``.

    ``shared_params`` (optional) is a param tree used by EVERY block — the
    jumbo architecture's shared CLS MLP is exactly this shape. It is
    replicated across stages, ``block_fn`` is then called as
    ``block_fn(one_block_params, h, shared_params)``, and its gradient
    comes back correctly summed over stages (the replicated-input
    transpose is a ``psum`` over ``pipe``).

    ``rng`` (optional) enables stochastic blocks (dropout / droppath):
    ``block_fn`` is then called with a trailing PRNG key derived per
    (data-shard, global block index, microbatch) — every block application
    anywhere in the schedule draws an independent stream, exactly the
    independence structure the sequential path gets from flax folding the
    "dropout" stream per module path (masks differ from sequential
    execution, the distribution matches). Without it the schedule is
    deterministic and ``block_fn`` keeps its short signature.
    """
    n_stages = mesh.shape[axis]
    n_blocks = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_blocks % n_stages:
        raise ValueError(
            f"{n_blocks} blocks do not divide over {n_stages} pipeline stages"
        )
    batch = x.shape[0]
    if batch % microbatches:
        raise ValueError(
            f"batch {batch} not divisible into {microbatches} microbatches"
        )
    mb = batch // microbatches
    xm = x.reshape(microbatches, mb, *x.shape[1:])

    data_spec = data_axis if (data_axis and data_axis in mesh.shape) else None
    if data_spec and mb % mesh.shape[data_axis]:
        raise ValueError(
            f"microbatch size {mb} (batch {batch} / {microbatches} "
            f"microbatches) does not divide over the "
            f"{data_axis}={mesh.shape[data_axis]} mesh axis"
        )

    shared = {} if shared_params is None else shared_params
    bps = n_blocks // n_stages  # blocks per stage
    # a dummy key keeps the shard_map arity static when rng is unused
    rng_in = rng if rng is not None else jax.random.key(0)

    @partial(
        compat.shard_map,
        mesh=mesh,
        in_specs=(
            jax.tree_util.tree_map(lambda _: P(axis), stacked_params),
            P(None, data_spec),
            jax.tree_util.tree_map(lambda _: P(), shared),  # replicated
            P(),  # rng: replicated; decorrelated below by axis_index folds
        ),
        out_specs=P(None, data_spec),
        check_vma=False,
    )
    def run(local_params, x_local, shared_local, rng_local):
        stage = jax.lax.axis_index(axis)
        m = x_local.shape[0]
        if rng is not None and data_spec:
            # distinct dropout masks per data shard (the GSPMD sequential
            # path gets this for free from sharding the global mask)
            rng_local = jax.random.fold_in(
                rng_local, jax.lax.axis_index(data_axis)
            )

        def apply_stage(h, mb_idx):
            # each stage applies its contiguous slice of blocks in order
            def one(h, xs):
                p, local_idx = xs
                args = (p, h) if shared_params is None else (p, h, shared_local)
                if rng is None:
                    return block_fn(*args), None
                key = jax.random.fold_in(
                    jax.random.fold_in(rng_local, stage * bps + local_idx),
                    mb_idx,
                )
                return block_fn(*args, key), None

            h, _ = jax.lax.scan(one, h, (local_params, jnp.arange(bps)))
            return h

        def tick(carry, t):
            act, buf = carry
            inp = jnp.where(stage == 0, x_local[jnp.clip(t, 0, m - 1)], act)
            # stage s processes microbatch t - s at tick t (clamped ticks
            # compute garbage that is never collected)
            out = apply_stage(inp, jnp.clip(t - stage, 0, m - 1))
            nxt = jax.lax.ppermute(
                out, axis, [(i, (i + 1) % n_stages) for i in range(n_stages)]
            )
            out_idx = t - (n_stages - 1)
            collect = jnp.logical_and(stage == n_stages - 1, out_idx >= 0)
            slot = jnp.clip(out_idx, 0, m - 1)
            buf = buf.at[slot].set(jnp.where(collect, out, buf[slot]))
            return (nxt, buf), None

        buf0 = jnp.zeros_like(x_local)
        act0 = jnp.zeros_like(x_local[0])
        (_, buf), _ = jax.lax.scan(
            tick, (act0, buf0), jnp.arange(microbatches + n_stages - 1)
        )
        # only the last stage holds real outputs; masked psum replicates
        mine = jnp.where(stage == n_stages - 1, buf, jnp.zeros_like(buf))
        return jax.lax.psum(mine, axis)

    out = run(stacked_params, xm, shared, rng_in)
    return out.reshape(batch, *x.shape[1:])


def make_jumbo_pipeline_apply(
    cfg, *, mesh: Mesh, microbatches: int
) -> Callable[[dict, jax.Array], jax.Array]:
    """Build ``apply(encoder_params, x) -> x`` that pipelines a JumboViT
    encoder's ``block_*`` chain with the shared jumbo CLS MLP replicated
    across stages.

    The standalone block module is constructed HERE, at factory time —
    constructing flax modules inside another module's apply (e.g. from the
    ``blocks_override`` seam) is an ``AssignSubModuleError``.

    ``encoder_params`` is the encoder subtree of a real model
    (``block_0…block_{L-1}`` + ``jumbo_mlp`` + embed/ln/… — only the
    blocks and ``jumbo_mlp`` are read). ``x`` is the token sequence after
    embedding/CLS concat, i.e. the input to ``block_0``.
    """
    from jumbo_mae_tpu_tpu.models.config import maybe_remat
    from jumbo_mae_tpu_tpu.models.layers import JumboBlock, make_jumbo_mlp

    # name=None: a standalone block scopes the shared MLP under itself
    # via its attribute name, and we graft the shared params in per call.
    # maybe_remat: the pipeline must honor cfg.grad_ckpt like the
    # sequential encoder does — GPipe holds every in-flight microbatch's
    # activations, so dropping remat here would silently change the memory
    # profile of exactly the configs pipeline parallelism targets.
    block = maybe_remat(JumboBlock, cfg)(cfg, make_jumbo_mlp(cfg, name=None))

    def apply(
        encoder_params: dict, x: jax.Array, rng: jax.Array | None = None
    ) -> jax.Array:
        stacked, _ = stack_block_params(encoder_params)

        if rng is None:

            def block_fn(p, h, shared):
                # a standalone JumboBlock scopes the shared MLP under
                # itself; the encoder scopes it at the parent — graft it in
                return block.apply(
                    {"params": {**p, "jumbo_mlp": shared}}, h, True
                )

        else:

            def block_fn(p, h, shared, key):
                return block.apply(
                    {"params": {**p, "jumbo_mlp": shared}},
                    h,
                    False,
                    rngs={"dropout": key},
                )

        return gpipe(
            block_fn,
            stacked,
            x,
            mesh=mesh,
            microbatches=microbatches,
            shared_params=encoder_params["jumbo_mlp"],
            rng=rng,
        )

    return apply


def make_plain_pipeline_apply(
    cfg, *, mesh: Mesh, microbatches: int
) -> Callable[[dict, jax.Array], jax.Array]:
    """Build ``apply(params, x) -> x`` that pipelines a plain pre-norm
    block chain (``block_0…block_{L-1}`` of :class:`PlainBlock` — the MAE
    decoder's stack) over the mesh's ``pipe`` axis.

    Same factory pattern as :func:`make_jumbo_pipeline_apply` (module
    constructed at factory time, honors ``cfg.grad_ckpt``); the optional
    ``rng`` third argument enables dropout/droppath via gpipe's
    per-(shard, block, microbatch) key derivation."""
    from jumbo_mae_tpu_tpu.models.config import maybe_remat
    from jumbo_mae_tpu_tpu.models.layers import PlainBlock

    block = maybe_remat(PlainBlock, cfg)(cfg)

    def apply(
        params: dict, x: jax.Array, rng: jax.Array | None = None
    ) -> jax.Array:
        stacked, _ = stack_block_params(params)

        if rng is None:

            def block_fn(p, h):
                return block.apply({"params": p}, h, True)

        else:

            def block_fn(p, h, key):
                return block.apply(
                    {"params": p}, h, False, rngs={"dropout": key}
                )

        return gpipe(
            block_fn, stacked, x, mesh=mesh, microbatches=microbatches, rng=rng
        )

    return apply


def pipelined_jumbo_blocks_apply(
    cfg,
    encoder_params: dict,
    x: jax.Array,
    *,
    mesh: Mesh,
    microbatches: int,
) -> jax.Array:
    """One-shot convenience over :func:`make_jumbo_pipeline_apply` (module
    construction happens per call — use the factory from inside train
    steps)."""
    return make_jumbo_pipeline_apply(cfg, mesh=mesh, microbatches=microbatches)(
        encoder_params, x
    )


def pipelined_blocks_apply(
    block_module,
    params: dict,
    x: jax.Array,
    *,
    mesh: Mesh,
    microbatches: int,
    prefix: str = "block_",
) -> jax.Array:
    """Convenience wrapper: run a model's ``block_*`` chain (e.g. the MAE
    decoder's :class:`~jumbo_mae_tpu_tpu.models.layers.PlainBlock` stack)
    through :func:`gpipe`, taking the ordinary (unstacked) param layout."""
    stacked, _ = stack_block_params(params, prefix)

    def block_fn(p, h):
        return block_module.apply({"params": p}, h, True)

    return gpipe(
        block_fn, stacked, x, mesh=mesh, microbatches=microbatches
    )
