"""Ring attention: sequence/context parallelism over a mesh axis.

The reference had no long-context story at all (SURVEY §5 — attention
materializes (B,H,N,N) on one device, ``/root/reference/src/modeling.py:136-137``).
Here sequences shard over the ``seq`` mesh axis; each device holds a local
query block and the K/V blocks ROTATE around the ring via ``ppermute`` over
ICI neighbors, one hop per step, while a running online-softmax (m, l, acc)
merges each visiting block — exactly one full pass of K/V past every Q shard
in ``seq_parallel`` hops, with O(S/n) memory per device and compute that
overlaps the next hop's transfer (the collective-permute is issued before the
block's einsums, so XLA can run them concurrently).

API:
- :func:`ring_attention` — per-shard body (call inside ``shard_map``);
- :func:`ring_attention_sharded` — convenience wrapper that builds the
  ``shard_map`` over a mesh for globally-(B, S, H, D) inputs sharded on S.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from jumbo_mae_tpu_tpu.utils import compat

NEG_INF = -1e30


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_mask: jax.Array | None = None,
    *,
    axis_name: str,
    inner: str = "einsum",
    interpret: bool = False,
) -> jax.Array:
    """Online-softmax attention with K/V ring rotation over ``axis_name``.

    Shapes (per shard): (batch, local_seq, heads, head_dim); queries
    pre-scaled. ``kv_mask`` is an optional (batch, local_seq) bool marking
    which local *keys* are real — it rotates around the ring with its K/V
    block, so padded tokens (uneven sequence splits) never receive weight.
    Must run inside ``shard_map``/``pmap`` with ``axis_name`` bound. Returns
    the local query block's exact global attention output.

    ``inner="flash"`` computes each hop's local block with the Pallas
    flash kernels (``ops/pallas/attention.py``) and merges hops in
    log-sum-exp space — per-device score memory drops from
    O((S/n)²) to O(S/n), the right memory class for exactly the
    long-context regime ring attention targets (and the kernels are
    faster than einsum at those chunk lengths — PERF.md §Decisions 1).
    Requires ``kv_mask=None`` (even splits): the kernels mask trailing
    pad only, not arbitrary key masks.
    """
    if inner == "flash":
        if kv_mask is not None:
            raise ValueError(
                "inner='flash' supports even sequence splits only "
                "(kv_mask must be None — pad-free sharding)"
            )
        if jax.default_backend() == "tpu" or interpret:
            return _ring_attention_flash(
                q, k, v, axis_name=axis_name, interpret=interpret
            )
        # off-TPU there are no Mosaic kernels; silently running the Pallas
        # INTERPRETER would be orders of magnitude slower than the einsum
        # inner — fall back like ops/flash_attention.py does
        # (``interpret=True`` keeps the kernel path for CPU tests).
    n = jax.lax.psum(1, axis_name)
    bq, sq, h, d = q.shape

    m0 = jnp.full((bq, h, sq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, h, sq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, sq, h, d), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]
    masked = kv_mask is not None
    # The bias joins the scan carry and ring-rotates with its K/V block —
    # only pay that extra ppermute when a mask actually exists.
    bias0 = (
        jnp.where(kv_mask, 0.0, NEG_INF)[:, None, None, :] if masked else None
    )  # (b,1,1,k)

    def hop(carry, _):
        m, l, acc, k_cur, v_cur, bias = carry
        # issue the rotation FIRST so the compiler MAY overlap the transfer
        # with this block's math (standard ring-attention scheduling; actual
        # ICI/compute overlap is up to XLA's scheduler and has not been
        # profiled on multi-chip hardware — this sandbox has one chip, so
        # only the numerics/gradients of the ring are verified here)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        bias_nxt = (
            jax.lax.ppermute(bias, axis_name, perm) if masked else None
        )

        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k_cur, preferred_element_type=jnp.float32
        )
        if masked:
            s = s + bias
        m_new = jnp.maximum(m, s.max(-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + p.sum(-1, keepdims=True)
        acc = acc * alpha.transpose(0, 2, 1, 3) + jnp.einsum(
            "bhqk,bkhd->bqhd",
            p.astype(v_cur.dtype),
            v_cur,
            preferred_element_type=jnp.float32,
        )
        return (m_new, l, acc, k_nxt, v_nxt, bias_nxt), None

    (m, l, acc, *_), _ = jax.lax.scan(
        hop, (m0, l0, acc0, k, v, bias0), None, length=n
    )
    return (acc / l.transpose(0, 2, 1, 3)).astype(q.dtype)


def _ring_attention_flash(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    interpret: bool = False,
) -> jax.Array:
    """Flash-kernel hop body for :func:`ring_attention` (``inner="flash"``).

    Each visiting K/V block is attended with the O(chunk)-memory Pallas
    kernels via :func:`pallas_flash_attention_with_lse` — DIFFERENTIABLE
    in both outputs, so autodiff through the merge below produces the lse
    cotangents the weights depend on (a stopped-lse merge would silently
    drop the softmax-denominator gradient path). Hops combine in lse
    space: with ``out_h`` softmax-normalized over its block and
    ``exp(lse_h) = Σ_j exp(s_j)``, the running ``(out, lse)`` pair merges
    as a two-way log-sum-exp — numerically stable and exact. Per-device
    score memory is O(local_seq), the memory class ring attention exists
    for; the kernels are also faster than einsum at long chunk lengths
    (PERF.md §Decisions 1).
    """
    from jumbo_mae_tpu_tpu.ops.pallas.attention import (
        pallas_flash_attention_with_lse,
    )

    n = compat.axis_size(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    bq, sq, h, d = q.shape

    def hop(carry, _):
        out, lse, k_cur, v_cur = carry
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        out_h, lse_h = pallas_flash_attention_with_lse(
            q, k_cur, v_cur, 1024, 1024, interpret
        )
        lse_h = lse_h.reshape(bq, h, sq).transpose(0, 2, 1)[..., None]
        m_new = jnp.maximum(lse, lse_h)  # (b, sq, h, 1)
        w_prev = jnp.exp(lse - m_new)
        w_h = jnp.exp(lse_h - m_new)
        denom = w_prev + w_h
        out = out * (w_prev / denom) + out_h.astype(jnp.float32) * (
            w_h / denom
        )
        lse = m_new + jnp.log(denom)
        return (out, lse, k_nxt, v_nxt), None

    out0 = jnp.zeros((bq, sq, h, d), jnp.float32)
    lse0 = jnp.full((bq, sq, h, 1), NEG_INF, jnp.float32)
    (out, _, _, _), _ = jax.lax.scan(hop, (out0, lse0, k, v), None, length=n)
    return out.astype(q.dtype)


def ring_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    seq_axis: str = "seq",
    batch_axes=("data", "fsdp"),
    inner: str = "einsum",
    interpret: bool = False,
) -> jax.Array:
    """Explicit-mesh alias of :func:`ring_self_attention`: global
    (B, S, H, D) inputs with S sharded over ``seq_axis`` (and batch over
    ``batch_axes``); emits the identically sharded attention output."""
    return ring_self_attention(
        q, k, v, seq_axis=seq_axis, batch_axes=batch_axes, mesh=mesh,
        inner=inner, interpret=interpret,
    )


def ring_self_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    seq_axis: str = "seq",
    batch_axes=("data", "fsdp"),
    mesh: Mesh | None = None,
    inner: str = "einsum",
    interpret: bool = False,
) -> jax.Array:
    """Sequence-parallel self-attention, for use inside model code under
    ``jit``. Uses the *ambient* mesh by default (activate with
    ``utils.compat.set_mesh``) or an explicitly passed ``mesh``. Handles
    sequence lengths that don't divide the ``seq`` axis by zero-padding K/V
    and masking the pad keys (the mask ring-rotates with its block). Falls
    back to plain attention when no mesh is active or its ``seq`` axis is
    trivial.

    q, k, v: (batch, seq, heads, head_dim), queries pre-scaled.
    """
    shape = (mesh or compat.ambient_mesh()).shape
    n = shape.get(seq_axis, 1)
    if not n or n <= 1:
        from jumbo_mae_tpu_tpu.ops.flash_attention import xla_attention

        return xla_attention(q, k, v)

    b, s, h, d = q.shape
    s_pad = -(-s // n) * n
    pad = s_pad - s
    bspec = tuple(a for a in batch_axes if shape.get(a, 1) > 1) or None
    qkv_spec = P(bspec, seq_axis, None, None)
    if not pad:
        return compat.shard_map(
            partial(
                ring_attention,
                axis_name=seq_axis,
                inner=inner,
                interpret=interpret,
            ),
            mesh=mesh,
            in_specs=(qkv_spec, qkv_spec, qkv_spec),
            out_specs=qkv_spec,
            check_vma=False,
        )(q, k, v)
    if inner == "flash":
        raise ValueError(
            "inner='flash' requires the sequence length to divide the "
            f"'{seq_axis}' axis ({s} over {n} shards needs padding, and "
            "the flash kernels mask trailing pad only)"
        )
    widths = ((0, 0), (0, pad), (0, 0), (0, 0))
    q, k, v = (jnp.pad(x, widths) for x in (q, k, v))
    kv_mask = jnp.broadcast_to(jnp.arange(s_pad) < s, (b, s_pad))
    out = compat.shard_map(
        partial(ring_attention, axis_name=seq_axis),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, P(bspec, seq_axis)),
        out_specs=qkv_spec,
        check_vma=False,
    )(q, k, v, kv_mask)
    return out[:, :s]
