"""Device-mesh construction.

The reference's entire distributed story was ``jax.pmap(axis_name="batch")``
(``/root/reference/src/pretraining.py:125``) — pure data parallelism. Here the
runtime is an explicit ``jax.sharding.Mesh`` with up to four axes:

- ``data``  — batch sharding across slices/hosts (DCN-friendly outer axis);
- ``fsdp``  — batch sharding *and* parameter/optimizer sharding (ZeRO-3
  style), laid out on ICI;
- ``tensor`` — reserved for tensor-parallel experiments (size 1 by default);
- ``seq``   — sequence/context parallelism for ring attention (size 1 unless
  long-context is requested).

GSPMD inserts all-reduce / reduce-scatter / all-gather over the right fabric
from the sharding annotations; nothing in the framework issues collectives by
hand except the ``shard_map`` ring-attention path.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("data", "fsdp", "tensor", "seq")


@dataclass(frozen=True)
class MeshConfig:
    """Axis sizes; -1 on ``fsdp`` means "all remaining devices"."""

    data: int = 1
    fsdp: int = -1
    tensor: int = 1
    seq: int = 1

    def resolve(self, n_devices: int) -> tuple[int, int, int, int]:
        sizes = [self.data, self.fsdp, self.tensor, self.seq]
        if sizes.count(-1) > 1:
            raise ValueError("at most one mesh axis may be -1")
        known = int(np.prod([s for s in sizes if s != -1]))
        if -1 in sizes:
            if n_devices % known:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {known}"
                )
            sizes[sizes.index(-1)] = n_devices // known
        if int(np.prod(sizes)) > n_devices:
            raise ValueError(
                f"mesh {dict(zip(AXES, sizes))} needs more than the "
                f"{n_devices} available devices"
            )
        return tuple(sizes)  # type: ignore[return-value]


def create_mesh(
    config: MeshConfig | None = None, devices: list | None = None
) -> Mesh:
    """Build the global mesh. Axis order is (data, fsdp, tensor, seq) —
    outermost axis maps to the slowest fabric (DCN between slices), innermost
    to ICI neighbors, matching ``mesh_utils.create_device_mesh`` conventions.
    """
    devices = devices if devices is not None else jax.devices()
    config = config or MeshConfig()
    sizes = config.resolve(len(devices))
    n_used = int(np.prod(sizes))
    devices = devices[:n_used]  # explicit sub-mesh (tests, single-chip bench)
    from jax.experimental import mesh_utils

    if n_used == 1:
        dev_array = np.array(devices).reshape(sizes)
    else:
        dev_array = mesh_utils.create_device_mesh(sizes, devices=devices)
    return Mesh(dev_array, AXES)
