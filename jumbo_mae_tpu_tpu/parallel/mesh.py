"""Device-mesh construction.

The reference's entire distributed story was ``jax.pmap(axis_name="batch")``
(``/root/reference/src/pretraining.py:125``) — pure data parallelism. Here the
runtime is an explicit ``jax.sharding.Mesh`` with up to four axes:

- ``data``  — batch sharding across slices/hosts (DCN-friendly outer axis);
- ``fsdp``  — batch sharding *and* parameter/optimizer sharding (ZeRO-3
  style), laid out on ICI;
- ``tensor`` — reserved for tensor-parallel experiments (size 1 by default);
- ``seq``   — sequence/context parallelism for ring attention (size 1 unless
  long-context is requested).

GSPMD inserts all-reduce / reduce-scatter / all-gather over the right fabric
from the sharding annotations; nothing in the framework issues collectives by
hand except the ``shard_map`` ring-attention path.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("data", "fsdp", "tensor", "seq")


@dataclass(frozen=True)
class MeshConfig:
    """Axis sizes; -1 on ``fsdp`` means "all remaining devices".

    ``pipe > 1`` selects pipeline parallelism instead: the runtime builds a
    ``(data, pipe)`` mesh (``create_pipeline_mesh``) and streams
    ``pipe_microbatches`` microbatches through the GPipe schedule
    (``parallel/pipeline.py``). Mutually exclusive with fsdp/tensor/seq > 1.
    """

    data: int = 1
    fsdp: int = -1
    tensor: int = 1
    seq: int = 1
    pipe: int = 1
    pipe_microbatches: int = 0  # 0 → defaults to the pipe size
    # pretrain only: also depth-shard the MAE decoder stack over ``pipe``
    # (the pipe size must divide dec_layers)
    pipe_decoder: bool = False

    def validate_pipe(self) -> None:
        if self.pipe > 1 and any(
            s not in (1, -1) for s in (self.fsdp, self.tensor, self.seq)
        ):
            raise ValueError(
                "mesh.pipe composes with mesh.data only; set fsdp/tensor/seq "
                "to 1 (pipeline + FSDP/TP/SP composition is not wired)"
            )

    def resolve(self, n_devices: int) -> tuple[int, int, int, int]:
        if self.pipe > 1:
            # A flat (data, fsdp, tensor, seq) mesh cannot express pipeline
            # parallelism; silently dropping the knob would waste the pipe
            # axis. Callers must route through create_pipeline_mesh (the
            # CLI does: cli/train.py mesh.pipe branch).
            raise ValueError(
                "MeshConfig.pipe > 1 selects pipeline parallelism — build "
                "the mesh with create_pipeline_mesh, not create_mesh/resolve"
            )
        sizes = [self.data, self.fsdp, self.tensor, self.seq]
        if sizes.count(-1) > 1:
            raise ValueError("at most one mesh axis may be -1")
        known = int(np.prod([s for s in sizes if s != -1]))
        if -1 in sizes:
            if n_devices % known:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {known}"
                )
            sizes[sizes.index(-1)] = n_devices // known
        if int(np.prod(sizes)) > n_devices:
            raise ValueError(
                f"mesh {dict(zip(AXES, sizes))} needs more than the "
                f"{n_devices} available devices"
            )
        return tuple(sizes)  # type: ignore[return-value]


def plan_hybrid_mesh(
    sizes: tuple[int, int, int, int], n_slices: int
) -> tuple[tuple[int, ...], tuple[int, ...]]:
    """Split the resolved axis sizes into (per-slice ICI shape, DCN shape)
    for a multislice deployment: only the ``data`` axis may span slices
    (the slow DCN fabric carries gradient all-reduce, which overlaps well),
    while fsdp/tensor/seq — whose collectives sit on the critical path —
    stay inside a slice on ICI."""
    data, fsdp, tensor, seq = sizes
    if data % n_slices:
        raise ValueError(
            f"data axis ({data}) must be divisible by the slice count "
            f"({n_slices}) — only the data axis spans DCN"
        )
    return (data // n_slices, fsdp, tensor, seq), (n_slices, 1, 1, 1)


def mesh_strategy(slice_ids: list[int], sizes: tuple[int, int, int, int]) -> str:
    """Decide how to lay devices out: ``"hybrid"`` (slice-aligned
    ICI×DCN mesh) only when every slice is fully used AND the data axis is
    divisible by the slice count; otherwise ``"flat"`` — which always works
    (it is the pre-multislice behavior), just with suboptimal fabric
    placement, so a default config never hard-fails on multislice hardware.
    """
    n_slices = len(set(slice_ids))
    if n_slices <= 1:
        return "flat"
    per_slice_counts = {s: slice_ids.count(s) for s in set(slice_ids)}
    if len(set(per_slice_counts.values())) != 1:
        return "flat"  # truncated sub-mesh straddles a slice boundary
    if sizes[0] % n_slices:
        return "flat"
    return "hybrid"


def create_mesh(
    config: MeshConfig | None = None, devices: list | None = None
) -> Mesh:
    """Build the global mesh. Axis order is (data, fsdp, tensor, seq) —
    outermost axis maps to the slowest fabric (DCN between slices), innermost
    to ICI neighbors, matching ``mesh_utils.create_device_mesh`` conventions.

    Multislice (DCN) is detected from the devices' ``slice_index``: with more
    than one slice the mesh is built with ``create_hybrid_device_mesh`` so
    slice boundaries land exactly on the data axis — a flat
    ``create_device_mesh`` would interleave slices and put fsdp/tensor
    collectives onto DCN.
    """
    devices = devices if devices is not None else jax.devices()
    config = config or MeshConfig()
    sizes = config.resolve(len(devices))
    n_used = int(np.prod(sizes))
    devices = devices[:n_used]  # explicit sub-mesh (tests, single-chip bench)
    from jax.experimental import mesh_utils

    slice_ids = [getattr(d, "slice_index", 0) for d in devices]
    strategy = mesh_strategy(slice_ids, sizes)
    n_slices = len(set(slice_ids))
    if strategy == "hybrid":
        per_slice, dcn = plan_hybrid_mesh(sizes, n_slices)
        dev_array = mesh_utils.create_hybrid_device_mesh(
            per_slice, dcn, devices=devices
        )
    else:
        if n_slices > 1:
            print(
                f"[mesh] WARNING: {n_slices} slices but mesh "
                f"{dict(zip(AXES, sizes))} is not slice-aligned (data axis "
                f"must be a multiple of {n_slices} and use every device); "
                "building a flat mesh — fsdp/tensor collectives may ride DCN"
            )
        if n_used == 1:
            dev_array = np.array(devices).reshape(sizes)
        else:
            dev_array = mesh_utils.create_device_mesh(sizes, devices=devices)
    return Mesh(dev_array, AXES)
