"""Run configuration: a dataclass tree with YAML recipes + CLI overrides.

Replaces the reference's config story — bash scripts passing ~50 argparse
flags per entry point (``/root/reference/src/main_pretrain.py:98-167``,
``/root/reference/config/*.sh``) — with typed recipe files. Epoch→step
arithmetic the reference did in shell (``$((1281167 * EPOCHS / BATCH))``,
``/root/reference/config/ft.sh:40-43``) is a config-time helper here
(``epochs:`` keys), and seeds default to fixed values, not ``random.randint``
(defect #7).

Override grammar: ``--set optim.learning_rate=1e-3 data.workers=0`` — dotted
paths into the tree, values parsed as YAML scalars.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Literal

import yaml

from jumbo_mae_tpu_tpu.data.loader import DataConfig
from jumbo_mae_tpu_tpu.parallel.mesh import MeshConfig
from jumbo_mae_tpu_tpu.train.checkpoint import CheckpointConfig
from jumbo_mae_tpu_tpu.train.optim import OptimConfig

IMAGENET_TRAIN_SIZE = 1_281_167

Mode = Literal["pretrain", "finetune", "linear"]


@dataclass(frozen=True)
class ModelConfig:
    """Encoder/decoder selection: a preset name plus field overrides."""

    preset: str = "vit_b16"
    overrides: dict[str, Any] = field(default_factory=dict)
    # decoder (pretrain only). The common knobs are first-class fields; every
    # other DecoderConfig field (dropout/droppath/layerscale/grad_ckpt/
    # remat_policy/attn_impl/ring_inner) is reachable via ``dec_overrides``,
    # mirroring the encoder's ``overrides`` (parity: the reference's
    # --dec-dropout/--dec-droppath/--dec-layerscale flags,
    # /root/reference/src/main_pretrain.py).
    dec_layers: int = 8
    dec_dim: int = 512
    dec_heads: int = 16
    dec_dtype: str = "bfloat16"
    dec_overrides: dict[str, Any] = field(default_factory=dict)
    norm_pix_loss: bool = True
    # classifier head (finetune/linear only)
    mixup: float = 0.0
    cutmix: float = 0.0
    label_smoothing: float = 0.0
    criterion: str = "ce"


@dataclass(frozen=True)
class RunConfig:
    mode: Mode = "pretrain"
    name: str = "run"
    output_dir: str = "runs"
    seed: int = 0
    init_seed: int = 0

    training_steps: int = 100
    log_interval: int = 50
    eval_interval: int = 1000
    # checkpoint cadence decoupled from eval: ckpt_every > 0 also saves a
    # checkpoint every N steps (no eval pass attached). 0 keeps the legacy
    # behavior — checkpoints ride eval boundaries only. tools/goodput_doctor
    # recommends a concrete value from measured save cost and failure rate.
    ckpt_every: int = 0

    train_batch_size: int = 256  # GLOBAL batch
    valid_batch_size: int = 256
    grad_accum: int = 1

    synthetic_data: bool = False
    sanity_eval: bool = True
    # evaluate-and-exit: restore weights (run.pretrained_ckpt or run.resume)
    # and run one full validation pass — no training. Beyond the reference
    # (its eval only ever runs inline in the train loop). eval_which picks
    # the checkpoint slot restored under run.resume: the rolling "last"
    # (resume semantics) or the metric-best "best".
    eval_only: bool = False
    eval_which: str = "last"
    resume: bool = False
    pretrained_ckpt: str = ""
    profile_dir: str = ""
    # resilience (jumbo_mae_tpu_tpu/faults): the divergence sentinel skips
    # non-finite steps on device and, after sentinel_patience consecutive
    # bad steps (skips or loss spikes above sentinel_spike_factor x EMA),
    # rolls back to the last checkpoint with the data cursor restored —
    # giving up after sentinel_max_rollbacks. `faults` holds a fault-
    # injection plan (GRAFT_FAULTS grammar, see faults/inject.py) — chaos
    # testing only; empty means the env var (if any) stays in charge.
    sentinel: bool = True
    sentinel_patience: int = 3
    sentinel_spike_factor: float = 10.0
    sentinel_ema_beta: float = 0.98
    sentinel_max_rollbacks: int = 3
    faults: str = ""
    # diagnostics (obs/modelstats, obs/journal, obs/flightrec):
    # diag_every > 0 compiles per-layer-group grad/param/update-ratio stats
    # + the loss batch's finite fraction into the train step (one extra
    # (groups, 3) array out; the base program is untouched at 0) and
    # fetches/publishes them every diag_every steps. `journal` writes the
    # append-only crash-safe run journal under <output_dir>/<name>/journal/.
    # flightrec_steps sizes the crash flight recorder's per-step ring
    # buffer (0 disables black-box dumps entirely).
    diag_every: int = 0
    journal: bool = True
    flightrec_steps: int = 256
    # retrace sentinel (obs/retrace.py): after warmup, any XLA recompile
    # journals a `retrace` event with shape/dtype-diff attribution and
    # warns. Costs one jax.monitoring listener + a dict lookup per step.
    retrace: bool = True
    # telemetry (jumbo_mae_tpu_tpu/obs): metrics are always *recorded*; the
    # exporter serving them over HTTP (/metrics Prometheus text, /healthz)
    # is opt-in. Port 0 binds any free port (the chosen one is printed).
    telemetry: bool = False
    telemetry_port: int = 9100
    telemetry_host: str = "0.0.0.0"
    # fleet health (obs/fleet.py): every process atomically rewrites a
    # per-host beacon under <run_dir>/fleet/ (step, step-time EMA, data-wait
    # fraction, shard retries/quarantines, sentinel bad steps, heartbeat);
    # host 0 aggregates the beacon dir into fleet_*{host=} gauges, journals
    # fleet_straggler / fleet_host_lost / fleet_host_rejoined transitions,
    # and feeds /healthz (degraded is soft — never a 503). A host is a
    # straggler when it trails the fleet-max step by fleet_lag_steps or its
    # step-time EMA exceeds fleet_ratio x the fleet median; lost when its
    # heartbeat is older than fleet_dead_after_s.
    fleet: bool = True
    fleet_lag_steps: int = 2
    fleet_ratio: float = 1.5
    fleet_dead_after_s: float = 60.0
    # elastic fleet training (train/elastic.py + obs/hangwatch.py): the hang
    # watchdog kills a process whose step makes no progress for
    # hangwatch_deadline_s seconds (0 = disabled; compile/eval/restore pause
    # it via expected() windows) with EXIT_HANG so the supervisor restarts
    # it. The supervisor (cli/train.py --elastic N) restarts a broken fleet
    # from the last committed checkpoint at the surviving world size, under
    # a budget of elastic_max_restarts with exponential backoff
    # (elastic_backoff_s doubling to elastic_backoff_cap_s); a host whose
    # beacon goes stale for elastic_wedge_after_s while its process lives is
    # treated as wedged supervisor-side; after a down-size, a graceful
    # restart back to full world size is attempted every
    # elastic_rejoin_after_s seconds.
    hangwatch_deadline_s: float = 0.0
    elastic_max_restarts: int = 8
    elastic_backoff_s: float = 1.0
    elastic_backoff_cap_s: float = 60.0
    elastic_wedge_after_s: float = 0.0
    elastic_rejoin_after_s: float = 30.0
    # memory observability (obs/memwatch.py): sample device/host memory per
    # log window (and per /metrics scrape when serving), journal mem_sample
    # snapshots, publish mem_* gauges, and run the leak sentinel — a robust
    # RSS slope over memwatch_leak_window samples exceeding memwatch_leak_mb
    # journals mem_leak_suspect naming the fastest-growing component, dumps
    # the flight recorder, and latches /healthz degraded.
    memwatch: bool = True
    memwatch_leak_window: int = 12
    memwatch_leak_mb: float = 32.0
    # serving SLOs (jumbo_mae_tpu_tpu/obs/slo.py): objectives like
    # "p99_latency_ms<=250;success_rate>=0.99" evaluated over a rolling
    # slow window with a fast confirmation window (0 = window_s / 12);
    # breaches above burn_threshold latch the degraded flag in /healthz
    # and publish the slo_* gauges. Empty = no SLO tracking.
    slo: str = ""
    slo_window_s: float = 60.0
    slo_fast_window_s: float = 0.0
    slo_burn_threshold: float = 1.0
    # continuous deployment (serve/publisher.py): publish_dir non-empty
    # turns on the gated train→serve weights publisher — every checkpoint
    # that passes the gates (finite-loss window since the last save,
    # sentinel-clean, at least publish_min_interval_steps since the last
    # publish, and — when publish_metric_key is set — the eval metric
    # above/below publish_metric_floor per publish_metric_sense) is
    # exported as an inference-ready artifact into publish_dir (the
    # directory `predict --swap-watch` polls). publish_quant "int8"
    # quantizes matmul weights at publish time (infer/quant.py);
    # "none" ships f32. Deltas ride against the last published tree;
    # a full tree is forced every publish_full_every artifacts.
    publish_dir: str = ""
    publish_quant: str = "int8"
    publish_min_interval_steps: int = 0
    publish_full_every: int = 8
    publish_metric_key: str = ""
    publish_metric_floor: float = 0.0
    publish_metric_sense: str = "below"
    # write the host-side span timeline (chrome://tracing / Perfetto JSON)
    # here at the end of the run; complements profile_dir's XLA device trace
    chrome_trace: str = ""
    use_wandb: bool = True
    wandb_project: str = ""
    wandb_entity: str = ""
    wandb_tags: tuple = ()
    wandb_id: str = ""  # stable id → resume the same wandb run on restart


@dataclass(frozen=True)
class TrainConfig:
    run: RunConfig = field(default_factory=RunConfig)
    model: ModelConfig = field(default_factory=ModelConfig)
    optim: OptimConfig = field(default_factory=OptimConfig)
    data: DataConfig = field(default_factory=DataConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)

    def checkpoint_config(self) -> CheckpointConfig:
        best_by_loss = self.run.mode == "pretrain"
        return CheckpointConfig(
            directory=str(Path(self.run.output_dir) / self.run.name / "ckpt"),
            best_mode="min" if best_by_loss else "max",
            metric_key="val/loss" if best_by_loss else "val/acc1",
        )


def steps_from_epochs(
    epochs: float, global_batch: int, dataset_size: int = IMAGENET_TRAIN_SIZE
) -> int:
    return int(dataset_size * epochs / global_batch)


_SECTIONS = {
    "run": RunConfig,
    "model": ModelConfig,
    "optim": OptimConfig,
    "data": DataConfig,
    "mesh": MeshConfig,
}


def _coerce(cls, raw: dict) -> Any:
    names = {f.name for f in dataclasses.fields(cls)}
    unknown = set(raw) - names
    if unknown:
        raise ValueError(f"unknown {cls.__name__} keys: {sorted(unknown)}")
    return cls(**raw)


def _resolve_epochs(doc: dict) -> dict:
    """Allow ``epochs`` / ``warmup_epochs`` in run/optim sections; converted
    against the global train batch size."""
    doc = {k: dict(v) if isinstance(v, dict) else v for k, v in doc.items()}
    run = doc.get("run", {})
    batch = run.get("train_batch_size", RunConfig.train_batch_size)
    # One source of truth for the dataset size: data.dataset_size wins, a
    # top-level dataset_size is accepted as shorthand, then the ImageNet
    # constant. The resolved value feeds BOTH the epochs→steps conversion
    # and the resume data cursor (cli/train.py).
    top_level = doc.pop("dataset_size", None)
    data_sec = doc.setdefault("data", {})
    dataset = data_sec.get("dataset_size", top_level)
    if dataset is None:
        dataset = IMAGENET_TRAIN_SIZE
    elif not isinstance(dataset, int) or isinstance(dataset, bool) or dataset <= 0:
        # it feeds both epochs→steps and the resume cursor — fail loudly
        raise ValueError(f"dataset_size must be a positive int, got {dataset!r}")
    data_sec["dataset_size"] = dataset
    if "epochs" in run:
        run["training_steps"] = steps_from_epochs(run.pop("epochs"), batch, dataset)
    optim = doc.get("optim", {})
    if "warmup_epochs" in optim:
        optim["warmup_steps"] = steps_from_epochs(
            optim.pop("warmup_epochs"), batch, dataset
        )
    optim.setdefault("training_steps", run.get("training_steps", RunConfig.training_steps))
    doc["run"], doc["optim"] = run, optim
    return doc


def config_from_dict(doc: dict) -> TrainConfig:
    doc = _resolve_epochs(doc or {})
    unknown = set(doc) - set(_SECTIONS)
    if unknown:
        raise ValueError(f"unknown config sections: {sorted(unknown)}")
    return TrainConfig(
        **{sec: _coerce(cls, doc.get(sec, {})) for sec, cls in _SECTIONS.items()}
    )


def _parse_value(text: str) -> Any:
    value = yaml.safe_load(text)
    if isinstance(value, str):
        # YAML 1.1 doesn't recognize dot-less scientific notation ("1e-3")
        try:
            return float(value)
        except ValueError:
            return value
    return value


def apply_overrides(doc: dict, overrides: list[str]) -> dict:
    doc = {k: dict(v) if isinstance(v, dict) else v for k, v in doc.items()}
    for item in overrides:
        if "=" not in item:
            raise ValueError(f"override must be key.path=value, got {item!r}")
        path, value = item.split("=", 1)
        keys = path.split(".")
        node = doc
        for k in keys[:-1]:
            node = node.setdefault(k, {})
            if not isinstance(node, dict):
                raise ValueError(f"cannot override through scalar at {k!r}")
        node[keys[-1]] = _parse_value(value)
    return doc


def load_config(
    path: str | Path | None = None, overrides: list[str] | None = None
) -> TrainConfig:
    doc: dict = {}
    if path is not None:
        doc = yaml.safe_load(Path(path).read_text()) or {}
    doc = apply_overrides(doc, overrides or [])
    return config_from_dict(doc)


def config_to_dict(cfg: TrainConfig) -> dict:
    return dataclasses.asdict(cfg)
