"""jumbo_mae_tpu_tpu — a TPU-native JAX framework for Jumbo Masked Autoencoders.

A ground-up GSPMD/pjit rebuild of the capabilities of
``antofuller/jumbo_mae_tpu`` (mounted read-only at ``/root/reference``):
MAE pretraining, supervised finetuning and linear probing of "Jumbo" ViTs
(multiple CLS tokens mixed by a shared wide MLP each layer) on ImageNet-1k
style tar shards, across TPU pod slices.

Design stance (see SURVEY.md §7):

- one ``jax.jit``-compiled train step over an explicit ``Mesh(("data","fsdp"))``
  with ``NamedSharding`` — no ``pmap`` anywhere;
- gradient accumulation as a ``lax.scan`` inside the step, not a host-visible
  micro-step state machine;
- a single fold-in RNG (seed ⊕ process ⊕ step ⊕ stream) instead of threaded
  split keys — reproducible and immune to the reference's RNG-shadowing defect
  (``/root/reference/src/finetuning.py:136-154``);
- torch-free streaming input pipeline with device-side prefetch;
- Orbax checkpointing of the full train state with true resume;
- Pallas kernels for the hot attention path, ring attention over a mesh axis
  for long sequences.
"""

__version__ = "0.1.0"
