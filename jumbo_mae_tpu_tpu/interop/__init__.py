from jumbo_mae_tpu_tpu.interop.reference_convert import (
    reference_encoder_to_jumbo,
    reference_head_batch_stats_to_jumbo,
    reference_pretrain_to_jumbo,
)
from jumbo_mae_tpu_tpu.interop.torch_convert import (
    flax_to_torch_state,
    timm_plain_vit_to_jumbo_state,
    torch_to_flax_params,
)

__all__ = [
    "flax_to_torch_state",
    "timm_plain_vit_to_jumbo_state",
    "torch_to_flax_params",
    "reference_encoder_to_jumbo",
    "reference_head_batch_stats_to_jumbo",
    "reference_pretrain_to_jumbo",
]
