from jumbo_mae_tpu_tpu.interop.torch_convert import (
    flax_to_torch_state,
    torch_to_flax_params,
)

__all__ = ["flax_to_torch_state", "torch_to_flax_params"]
