"""Convert reference-layout flax checkpoints to this framework's layout.

The reference trainer (``/root/reference/src/modeling.py:221-298``,
``/root/reference/src/pretraining.py:76-122``) serializes param trees with
its own module names (``wq/wk/wv/wo``, ``w1/w2``, ``norm1..3``, ``scale1..3``,
``layer_N``, ``dec_layer_N``, ``image_mask_embedding`` …). A user migrating a
reference ``.msgpack`` checkpoint into this framework loads it with
``flax.serialization.msgpack_restore`` and passes the tree through one of
these functions; the result drops straight into ``JumboViT`` /
``MAEPretrainModel``.

Only array renames/re-nesting happen here — no transposes are needed because
both sides are flax (same kernel layouts). The mapping is exercised end-to-end
by ``tests/test_reference_parity.py``, which asserts forward-output equality
between the two model implementations under converted weights.
"""

from __future__ import annotations

__all__ = [
    "reference_encoder_to_jumbo",
    "reference_pretrain_to_jumbo",
    "reference_head_batch_stats_to_jumbo",
]

_ATTN_MAP = {"wq": "q", "wk": "k", "wv": "v", "wo": "out"}
_MLP_MAP = {"w1": "fc1", "w2": "fc2"}


def _convert_mlp(ff: dict) -> dict:
    return {_MLP_MAP[k]: v for k, v in ff.items()}


def _convert_block(layer: dict, *, jumbo: bool) -> dict:
    """Reference ``JumboLayer``/``ViTLayer`` params → ``JumboBlock``/
    ``PlainBlock`` params."""
    out: dict = {
        "attn": {_ATTN_MAP[k]: v for k, v in layer["attn"].items()},
        "mlp": _convert_mlp(layer["ff"]),
    }
    norms = ("norm1", "norm2", "norm3") if jumbo else ("norm1", "norm2")
    scales = ("scale1", "scale2", "scale3") if jumbo else ("scale1", "scale2")
    for n in norms:
        if n in layer:
            out["ln" + n[-1]] = layer[n]
    for s in scales:
        if s in layer:
            out["ls" + s[-1]] = layer[s]
    return out


def _numbered(tree: dict, prefix: str) -> list[str]:
    keys = [k for k in tree if k.startswith(prefix)]
    return sorted(keys, key=lambda k: int(k.rsplit("_", 1)[1]))


def reference_encoder_to_jumbo(ref: dict) -> dict:
    """Reference ``ViT`` param tree → ``JumboViT`` param tree.

    Accepts the bare encoder tree (what sits under ``"model"`` in a reference
    checkpoint, ``/root/reference/src/pretraining.py:214``).
    """
    out: dict = {"cls_tokens": ref["cls_tokens"]}

    embed: dict = {"proj": ref["embed"]["wte"]}
    if "wpe" in ref["embed"]:
        embed["pos_embed"] = ref["embed"]["wpe"]
    out["embed"] = embed

    out["jumbo_mlp"] = _convert_mlp(ref["jumbo_mlp"])
    for key in _numbered(ref, "layer_"):
        idx = key.rsplit("_", 1)[1]
        out[f"block_{idx}"] = _convert_block(ref[key], jumbo=True)
    out["ln"] = ref["norm"]

    if "head" in ref:
        head: dict = {}
        if "Dense_0" in ref["head"]:
            head["fc"] = ref["head"]["Dense_0"]
        if "BatchNorm_0" in ref["head"]:
            head["bn"] = ref["head"]["BatchNorm_0"]
        out["head"] = head
    return out


def _reference_decoder_to_jumbo(ref: dict) -> dict:
    """Reference ``MAEDecoder`` param tree → ``MAEDecoder`` (this package)."""
    out: dict = {}
    for key in _numbered(ref, "dec_layer_"):
        idx = key.rsplit("_", 1)[1]
        out[f"block_{idx}"] = _convert_block(ref[key], jumbo=False)
    out["ln"] = ref["dec_norm"]
    return out


def reference_pretrain_to_jumbo(ref: dict) -> dict:
    """Reference ``PretrainModule`` param tree → ``MAEPretrainModel`` tree.

    Reference layout: ``model`` (ViT), ``decoder_model`` (MAEDecoder),
    ``image_mask_embedding``, ``decoder_proj``, ``decoder_image_output``
    (``/root/reference/src/pretraining.py:82-85``).
    """
    return {
        "encoder": reference_encoder_to_jumbo(ref["model"]),
        "decoder": _reference_decoder_to_jumbo(ref["decoder_model"]),
        "mask_token": ref["image_mask_embedding"],
        "decoder_proj": ref["decoder_proj"],
        "pixel_proj": ref["decoder_image_output"],
    }


def reference_head_batch_stats_to_jumbo(batch_stats: dict) -> dict:
    """Reference linear-probe BatchNorm running stats
    (``{"head": {"BatchNorm_0": {"mean", "var"}}}``) → this layout
    (``{"head": {"bn": {...}}}``)."""
    bn = batch_stats["head"]["BatchNorm_0"]
    return {"head": {"bn": {"mean": bn["mean"], "var": bn["var"]}}}
