"""Bidirectional flax ↔ PyTorch/timm-style checkpoint conversion — for the
ACTUAL jumbo layout.

The reference shipped converters targeting its upstream's plain-ViT tree
(``/root/reference/scripts/convert_flax_to_pytorch.py:25-91``,
``convert_pytorch_to_flax.py:24-101``); they silently ignored every
jumbo-specific parameter (3 CLS tokens, shared jumbo MLP, ``norm3``/``ls3``
per block) — SURVEY defect #4. These converters handle the full jumbo
encoder:

torch-side naming (timm ViT grammar, extended):

- ``cls_tokens``                 (1, K, D)        — K=3 CLS tokens
- ``patch_embed.proj.{weight,bias}``; ``pos_embed`` (1, N, D) patch-only grid
- ``blocks.{i}.norm{1,2,3}.*``, ``blocks.{i}.attn.qkv.{weight,bias}`` (fused),
  ``blocks.{i}.attn.proj.*``, ``blocks.{i}.mlp.fc{1,2}.*``,
  ``blocks.{i}.ls{1,2,3}.gamma`` (LayerScale)
- ``jumbo_mlp.fc{1,2}.*``        — stored ONCE (shared across blocks)
- ``norm.*``, ``head.{weight,bias}``, ``head_bn.{weight,bias,running_mean,running_var}``

Round-trip is exact (pure transpose/reshape/concat algebra, no recompute).
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "flax_to_torch_state",
    "timm_plain_vit_to_jumbo_state",
    "torch_to_flax_params",
]


def _np(x) -> np.ndarray:
    return np.asarray(x)


def _find_encoder(params: dict) -> dict:
    for key in ("model", "encoder"):
        if key in params:
            return params[key]
    if "cls_tokens" in params:
        return params
    raise KeyError(
        "no encoder subtree found (expected 'model', 'encoder', or a bare "
        f"encoder tree); top-level keys: {sorted(params)}"
    )


def _fuse_qkv(attn: dict) -> tuple[np.ndarray, np.ndarray]:
    """flax q/k/v DenseGeneral kernels (D, H, hd) → torch fused qkv
    (3D, D) weight + (3D,) bias, rows ordered [q; k; v]."""
    ws, bs = [], []
    for name in ("q", "k", "v"):
        k = _np(attn[name]["kernel"])
        d = k.shape[0]
        ws.append(k.reshape(d, -1).T)  # (D_out, D_in)
        bs.append(_np(attn[name]["bias"]).reshape(-1))
    return np.concatenate(ws, axis=0), np.concatenate(bs, axis=0)


def _unfuse_qkv(weight: np.ndarray, bias: np.ndarray, heads: int) -> dict:
    d = weight.shape[1]
    head_dim = d // heads
    out = {}
    for i, name in enumerate(("q", "k", "v")):
        w = weight[i * d : (i + 1) * d]  # (D, D)
        b = bias[i * d : (i + 1) * d]
        out[name] = {
            "kernel": w.T.reshape(d, heads, head_dim),
            "bias": b.reshape(heads, head_dim),
        }
    return out


def _linear_to_torch(mod: dict) -> dict[str, np.ndarray]:
    return {"weight": _np(mod["kernel"]).T, "bias": _np(mod["bias"])}


def _linear_from_torch(weight: np.ndarray, bias: np.ndarray) -> dict:
    return {"kernel": _np(weight).T, "bias": _np(bias)}


def _norm_to_torch(mod: dict) -> dict[str, np.ndarray]:
    return {"weight": _np(mod["scale"]), "bias": _np(mod["bias"])}


def flax_to_torch_state(params: dict, batch_stats: dict | None = None) -> dict:
    """Convert a jumbo encoder param tree (a ``ClassificationModel``/
    ``MAEPretrainModel`` tree or a bare ``JumboViT`` tree) to a torch-style
    flat state dict of numpy arrays (wrap in ``torch.from_numpy`` to save)."""
    enc = _find_encoder(params)
    out: dict[str, np.ndarray] = {}

    out["cls_tokens"] = _np(enc["cls_tokens"])
    embed = enc["embed"]
    # flax conv kernel (p, p, 3, D) → torch (D, 3, p, p)
    out["patch_embed.proj.weight"] = _np(embed["proj"]["kernel"]).transpose(3, 2, 0, 1)
    out["patch_embed.proj.bias"] = _np(embed["proj"]["bias"])
    if "pos_embed" in embed:
        grid = _np(embed["pos_embed"])  # (gh, gw, D)
        out["pos_embed"] = grid.reshape(1, -1, grid.shape[-1])

    blocks = sorted(
        (k for k in enc if k.startswith("block_")), key=lambda k: int(k.split("_")[1])
    )
    for i, bk in enumerate(blocks):
        blk = enc[bk]
        p = f"blocks.{i}."
        w, b = _fuse_qkv(blk["attn"])
        out[p + "attn.qkv.weight"], out[p + "attn.qkv.bias"] = w, b
        proj_k = _np(blk["attn"]["out"]["kernel"])  # (H, hd, D)
        d = proj_k.shape[-1]
        out[p + "attn.proj.weight"] = proj_k.reshape(-1, d).T
        out[p + "attn.proj.bias"] = _np(blk["attn"]["out"]["bias"])
        for ln in ("ln1", "ln2", "ln3"):
            if ln in blk:
                tn = _norm_to_torch(blk[ln])
                out[p + f"norm{ln[-1]}.weight"] = tn["weight"]
                out[p + f"norm{ln[-1]}.bias"] = tn["bias"]
        for ls in ("ls1", "ls2", "ls3"):
            if ls in blk:
                out[p + f"{ls}.gamma"] = _np(blk[ls])
        for fc in ("fc1", "fc2"):
            lt = _linear_to_torch(blk["mlp"][fc])
            out[p + f"mlp.{fc}.weight"] = lt["weight"]
            out[p + f"mlp.{fc}.bias"] = lt["bias"]

    for fc in ("fc1", "fc2"):
        lt = _linear_to_torch(enc["jumbo_mlp"][fc])
        out[f"jumbo_mlp.{fc}.weight"] = lt["weight"]
        out[f"jumbo_mlp.{fc}.bias"] = lt["bias"]

    tn = _norm_to_torch(enc["ln"])
    out["norm.weight"], out["norm.bias"] = tn["weight"], tn["bias"]

    if "head" in enc:
        head = enc["head"]
        if "fc" in head:
            lt = _linear_to_torch(head["fc"])
            out["head.weight"], out["head.bias"] = lt["weight"], lt["bias"]
        if "bn" in head:
            out["head_bn.weight"] = _np(head["bn"]["scale"])
            out["head_bn.bias"] = _np(head["bn"]["bias"])
    if batch_stats is not None:
        bn_stats = _find_encoder(batch_stats).get("head", {}).get("bn", {})
        if bn_stats:
            out["head_bn.running_mean"] = _np(bn_stats["mean"])
            out["head_bn.running_var"] = _np(bn_stats["var"])
    return out


def timm_plain_vit_to_jumbo_state(
    state: dict, *, num_cls_tokens: int = 3
) -> dict:
    """Adapt a PLAIN-ViT timm state dict (single ``cls_token``, CLS position
    baked into ``pos_embed``) to the extended-jumbo torch grammar consumed by
    :func:`torch_to_flax_params` — the timm-hub import workflow the reference
    documented (``/root/reference/scripts/convert_pytorch_to_flax.py:24-51``,
    ``/root/reference/README.md:130-146``), retargeted at the jumbo layout:

    - the CLS positional embedding folds into the token (as the reference
      did) and the token is tiled to ``num_cls_tokens`` — every jumbo CLS
      slot starts from the pretrained one;
    - ``pos_embed`` drops the CLS slot, leaving the patch-only grid;
    - blocks/norm keys already share the timm grammar and pass through;
    - the jumbo head reads the K CLS embeddings *concatenated* (input K·D,
      ``models/vit.py``), so the plain head weight (L, D) becomes
      (L, K·D) as K copies scaled by 1/K — when the K CLS slots carry the
      same embedding (as they do right after this import), the logits
      equal the plain model's;
    - there is no pretrained source for the shared jumbo MLP — it stays
      absent so a warm-start merge keeps its fresh init.
    """
    state = {k: _np(v) for k, v in state.items()}
    out = {k: v for k, v in state.items() if k not in ("cls_token", "pos_embed")}
    if "head.weight" in state:
        out["head.weight"] = np.tile(
            state["head.weight"] / num_cls_tokens, (1, num_cls_tokens)
        )
    cls = state.get("cls_token")  # (1, 1, D); absent on GAP-pooled models
    if "pos_embed" in state:
        pe = state["pos_embed"]  # (1, 1 + N, D) — CLS position first
        n_patches = pe.shape[1] - (1 if cls is not None else 0)
        side = int(round(np.sqrt(n_patches)))
        if cls is not None and side * side == n_patches:
            cls = cls + pe[:, :1, :]
            out["pos_embed"] = pe[:, 1:, :]
        else:
            # no CLS slot (GAP model) or non-square grid: pass through
            out["pos_embed"] = pe
    if cls is not None:
        out["cls_tokens"] = np.tile(cls, (1, num_cls_tokens, 1))
    # else: GAP-pooled source has no CLS token — leave cls_tokens absent so
    # a warm-start merge keeps the jumbo model's fresh init for them
    return out


def torch_to_flax_params(state: dict, *, heads: int) -> dict:
    """Inverse of :func:`flax_to_torch_state`: torch-style flat dict → bare
    jumbo encoder tree (nest under ``model``/``encoder`` for warm starts via
    ``load_pretrained_params``). ``heads`` is needed to re-split the fused
    qkv. BatchNorm running stats, if present, come back under the key
    ``__batch_stats__``."""
    state = {k: _np(v) for k, v in state.items()}
    enc: dict = {}

    if "cls_tokens" in state:
        enc["cls_tokens"] = state["cls_tokens"]
    # else: GAP-pooled source (no CLS) — warm-start merge keeps fresh init
    embed: dict = {
        "proj": {
            "kernel": state["patch_embed.proj.weight"].transpose(2, 3, 1, 0),
            "bias": state["patch_embed.proj.bias"],
        }
    }
    if "pos_embed" in state:
        pe = state["pos_embed"][0]  # (N, D)
        side = int(round(np.sqrt(pe.shape[0])))
        if side * side != pe.shape[0]:
            raise ValueError(f"non-square pos_embed with {pe.shape[0]} positions")
        embed["pos_embed"] = pe.reshape(side, side, pe.shape[-1])
    enc["embed"] = embed

    n_blocks = 1 + max(
        (int(k.split(".")[1]) for k in state if k.startswith("blocks.")), default=-1
    )
    for i in range(n_blocks):
        p = f"blocks.{i}."
        blk: dict = {}
        attn = _unfuse_qkv(state[p + "attn.qkv.weight"], state[p + "attn.qkv.bias"], heads)
        proj_w = state[p + "attn.proj.weight"]  # (D, D)
        d = proj_w.shape[0]
        attn["out"] = {
            "kernel": proj_w.T.reshape(heads, d // heads, d),
            "bias": state[p + "attn.proj.bias"],
        }
        blk["attn"] = attn
        for n in ("1", "2", "3"):
            if p + f"norm{n}.weight" in state:
                blk[f"ln{n}"] = {
                    "scale": state[p + f"norm{n}.weight"],
                    "bias": state[p + f"norm{n}.bias"],
                }
            if p + f"ls{n}.gamma" in state:
                blk[f"ls{n}"] = state[p + f"ls{n}.gamma"]
        blk["mlp"] = {
            fc: _linear_from_torch(state[p + f"mlp.{fc}.weight"], state[p + f"mlp.{fc}.bias"])
            for fc in ("fc1", "fc2")
        }
        enc[f"block_{i}"] = blk

    if "jumbo_mlp.fc1.weight" in state:
        enc["jumbo_mlp"] = {
            fc: _linear_from_torch(
                state[f"jumbo_mlp.{fc}.weight"], state[f"jumbo_mlp.{fc}.bias"]
            )
            for fc in ("fc1", "fc2")
        }
    # else: plain-ViT source (e.g. a timm hub checkpoint) has no shared
    # jumbo MLP — leave the key out; a warm-start merge keeps fresh init.
    enc["ln"] = {"scale": state["norm.weight"], "bias": state["norm.bias"]}

    head: dict = {}
    if "head.weight" in state:
        head["fc"] = _linear_from_torch(state["head.weight"], state["head.bias"])
    if "head_bn.weight" in state:
        head["bn"] = {"scale": state["head_bn.weight"], "bias": state["head_bn.bias"]}
    if head:
        enc["head"] = head
    if "head_bn.running_mean" in state:
        enc["__batch_stats__"] = {
            "head": {
                "bn": {
                    "mean": state["head_bn.running_mean"],
                    "var": state["head_bn.running_var"],
                }
            }
        }
    return enc
