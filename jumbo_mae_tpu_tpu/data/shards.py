"""Shard-list handling: brace expansion, deterministic shuffles, striping.

The reference streamed webdataset tars and relied on webdataset's
``SimpleShardList`` + ``detshuffle`` + ``wds.slice(process, None,
process_count)`` + ``split_by_worker`` chain to hand each DataLoader worker a
disjoint shard subset (``/root/reference/src/dataset.py:100-161``). This
module is a from-scratch equivalent with explicit, testable semantics:

- ``expand_shards`` understands the webdataset brace notation
  ``prefix-{000000..001023}.tar`` plus ``::``-joined multi-specs;
- ``shuffle_shards`` is a seeded Fisher–Yates keyed on (seed, epoch) so every
  process computes the SAME shard order without communicating;
- ``split_shards`` stripes that order first across processes then across
  workers — disjoint coverage, same contract as the reference chain.
"""

from __future__ import annotations

import random
import re

_BRACE = re.compile(r"\{(\d+)\.\.(\d+)\}")


def expand_shards(spec: str | list[str]) -> list[str]:
    """Expand a shard spec into an explicit URL list.

    ``spec`` may be a list (returned as-is), a ``::``-joined concatenation of
    specs, or a single pattern with at most one ``{AAAA..BBBB}`` numeric
    range (zero-padded to the width of the start literal).
    """
    if isinstance(spec, list):
        return list(spec)
    out: list[str] = []
    for part in spec.split("::"):
        m = _BRACE.search(part)
        if not m:
            out.append(part)
            continue
        start, end = m.group(1), m.group(2)
        width = len(start)
        lo, hi = int(start), int(end)
        if hi < lo:
            raise ValueError(f"empty brace range in {part!r}")
        for i in range(lo, hi + 1):
            out.append(part[: m.start()] + str(i).zfill(width) + part[m.end() :])
    return out


def shuffle_shards(shards: list[str], *, seed: int, epoch: int = 0) -> list[str]:
    """Deterministic shard-order shuffle, identical on every process.

    Keyed on (seed, epoch) so each pass over the dataset sees a fresh order
    while remaining reproducible (the reference's ``detshuffle`` epoch
    counter behaved the same way).
    """
    order = list(shards)
    random.Random(f"{seed}:{epoch}").shuffle(order)
    return order


def split_shards(
    shards: list[str],
    *,
    process_index: int = 0,
    process_count: int = 1,
    worker_index: int = 0,
    worker_count: int = 1,
) -> list[str]:
    """Stripe shards across processes, then across that process's workers.

    Guarantees: disjoint across (process, worker) pairs; union over all pairs
    covers every shard; stable for a fixed input order.
    """
    if not 0 <= process_index < process_count:
        raise ValueError(f"bad process {process_index}/{process_count}")
    if not 0 <= worker_index < worker_count:
        raise ValueError(f"bad worker {worker_index}/{worker_count}")
    per_process = shards[process_index::process_count]
    return per_process[worker_index::worker_count]
