"""Procedural labeled image dataset for end-to-end learning proofs.

The reference's entire quality story was its reproduced ImageNet
linear-probe table (``/root/reference/README.md:10-13``) — unrunnable in a
sandbox. This module gives the framework a self-contained stand-in with the
same *shape* of evidence: a distribution where MAE pretraining demonstrably
learns transferable structure, small enough to pretrain and probe on CPU in
a test.

Construction: each class is a fixed smooth random field (a sum of a few
low-frequency plane waves — class identity lives in the *global* spatial
structure). Each sample applies nuisance transforms that destroy pixel-level
class alignment: random translation (cyclic phase shift), per-channel color
gain/bias, contrast jitter, and additive noise. A linear probe straight on
pixels (or on a random-init encoder's features) does poorly because class
structure is entangled with the nuisances; an encoder pretrained to
reconstruct masked patches must model the global field to inpaint, which is
exactly the class-relevant information.
"""

from __future__ import annotations

import io

import numpy as np

__all__ = ["toy_examples", "toy_pretrain_hparams", "write_toy_shards"]


def _class_bank(classes: int, waves: int, rng: np.random.Generator):
    """Per-class plane-wave parameters, shapes (classes, waves).

    The PRIMARY wave's frequency pair is enumerated from a fixed list so no
    two classes share it — the per-sample translation absorbs phase, so
    phase/amplitude can never carry class identity; the frequency signature
    must, and it must be distinct by construction (random draws collide).
    Secondary waves add class-conditional texture at lower amplitude.
    """
    # HIGH-frequency signatures (4–12 cycles/image ≈ wavelength 2.7–8 px at
    # 32px, comparable to the 4px patch): a smooth low-frequency field is
    # locally interpolatable, so MAE inpainting never needs class identity
    # and the probe margin collapses (measured) — at texture scale, masked
    # patches can only be reconstructed by recognizing WHICH grating this
    # is, which is exactly the class.
    pairs = [
        (0.0, 4.0), (4.0, 0.0), (4.0, 4.0), (4.0, -4.0),
        (0.0, 8.0), (8.0, 0.0), (8.0, 8.0), (8.0, -8.0),
        (4.0, 8.0), (8.0, 4.0), (8.0, -4.0), (4.0, -8.0),
        (0.0, 12.0), (12.0, 0.0), (12.0, 12.0), (12.0, -12.0),
    ]
    if classes > len(pairs):
        raise ValueError(f"at most {len(pairs)} classes, got {classes}")
    fx = np.empty((classes, waves))
    fy = np.empty((classes, waves))
    fx[:, 0] = [pairs[i][0] for i in range(classes)]
    fy[:, 0] = [pairs[i][1] for i in range(classes)]
    if waves > 1:
        # low-amplitude low-frequency clutter shared across classes
        fx[:, 1:] = rng.integers(1, 3, size=(classes, waves - 1))
        fy[:, 1:] = rng.integers(1, 3, size=(classes, waves - 1)) * rng.choice(
            [-1.0, 1.0], size=(classes, waves - 1)
        )
    amp = np.full((classes, waves), 0.35)
    amp[:, 0] = 1.0  # the distinct texture wave dominates
    phase = rng.uniform(0, 2 * np.pi, size=(classes, waves))
    return fx, fy, amp, phase


def toy_examples(
    n: int,
    *,
    image_size: int = 32,
    classes: int = 10,
    seed: int = 0,
    waves: int = 2,
    noise: float = 0.1,
) -> tuple[np.ndarray, np.ndarray]:
    """Return ``(images uint8 (n,S,S,3), labels int32 (n,))``, deterministic
    in all arguments. Generate one array and slice train/val from it (as
    :func:`write_toy_shards` does) so the splits share a class bank without
    sharing samples."""
    bank_rng = np.random.default_rng(seed ^ 0xC1A55)
    fx, fy, amp, phase = _class_bank(classes, waves, bank_rng)

    rng = np.random.default_rng(seed)
    labels = rng.integers(0, classes, size=n).astype(np.int32)
    grid = np.arange(image_size, dtype=np.float64) * (2 * np.pi / image_size)
    gx = grid[None, :, None]  # broadcast over (y, x)
    gy = grid[None, None, :]

    # nuisances, drawn per sample
    shift = rng.uniform(0, 2 * np.pi, size=(n, 2))
    gain = rng.uniform(0.6, 1.4, size=(n, 3))
    bias = rng.uniform(-0.25, 0.25, size=(n, 3))
    contrast = rng.uniform(0.7, 1.3, size=(n,))
    eps = rng.normal(0, noise, size=(n, image_size, image_size, 3))

    images = np.empty((n, image_size, image_size, 3), np.uint8)
    for i in range(n):
        k = labels[i]
        field = np.zeros((1, image_size, image_size))
        for w in range(waves):
            field = field + amp[k, w] * np.sin(
                fx[k, w] * (gy + shift[i, 0])
                + fy[k, w] * (gx + shift[i, 1])
                + phase[k, w]
            )
        field = field[0] / np.sqrt(waves)  # (S, S), roughly unit scale
        x = contrast[i] * field[..., None] * gain[i] + bias[i]
        x = x + eps[i]
        images[i] = np.clip((x + 2.0) * (255.0 / 4.0), 0, 255).astype(np.uint8)
    return images, labels


def write_toy_shards(
    root,
    *,
    n_train: int = 2048,
    n_val: int = 512,
    shard_size: int = 512,
    image_size: int = 32,
    classes: int = 10,
    seed: int = 0,
) -> dict:
    """Materialize train/val tar shards (PNG payloads — lossless, the class
    signal is low-frequency but the probe margin shouldn't ride on JPEG
    behavior). Returns the brace-pattern URLs for DataConfig."""
    from pathlib import Path

    from PIL import Image

    from jumbo_mae_tpu_tpu.data.tario import write_tar_samples

    root = Path(root)
    root.mkdir(parents=True, exist_ok=True)
    images, labels = toy_examples(
        n_train + n_val, image_size=image_size, classes=classes, seed=seed
    )

    def encode(idx: int) -> dict:
        buf = io.BytesIO()
        Image.fromarray(images[idx], "RGB").save(buf, format="PNG")
        return {
            "__key__": f"toy{idx:06d}",
            "png": buf.getvalue(),
            "cls": str(int(labels[idx])).encode(),
        }

    def write_split(name: str, lo: int, hi: int) -> str:
        count = hi - lo
        n_shards = max(1, -(-count // shard_size))
        for s in range(n_shards):
            a = lo + s * shard_size
            b = min(lo + (s + 1) * shard_size, hi)
            write_tar_samples(
                str(root / f"{name}-{s:04d}.tar"),
                [encode(i) for i in range(a, b)],
            )
        return f"{root}/{name}-{{0000..{n_shards - 1:04d}}}.tar"

    return {
        "train": write_split("train", 0, n_train),
        "val": write_split("val", n_train, n_train + n_val),
        "classes": classes,
    }


def toy_pretrain_hparams(
    steps: int,
    *,
    dec_heads: int = 4,
    seed: int = 0,
    nu_dtype: str | None = None,
) -> list[str]:
    """CLI ``--set`` list for the canonical toy MAE pretrain — the
    learning proof's operating point (600 steps, t16 @32px/4px patches,
    2×64×4h decoder, lr 1.5e-3 / b2 0.95 / wd 0.05).

    Single source of truth shared by ``tests/test_learning_e2e.py`` and
    ``tools/toy_cls_probe_ab.py`` so the knob-A/B's baseline arm can
    never silently drift from the configuration the learning proof
    certifies. ``dec_heads`` / ``nu_dtype`` are the round-5
    convergence-A/B knobs."""
    out = [
        "run.mode=pretrain",
        f"run.seed={seed}",
        f"run.init_seed={seed}",
        f"run.training_steps={steps}",
        "run.train_batch_size=64",
        "run.valid_batch_size=64",
        f"run.eval_interval={steps}",
        "run.log_interval=200",
        "model.overrides={image_size: 32, patch_size: 4, layers: 4, "
        "posemb: sincos2d, dtype: float32, mask_ratio: 0.75}",
        "model.dec_layers=2",
        "model.dec_dim=64",
        f"model.dec_heads={dec_heads}",
        "model.dec_dtype=float32",
        "optim.learning_rate=1.5e-3",
        "optim.lr_scaling=none",
        "optim.warmup_steps=40",
        f"optim.training_steps={steps}",
        "optim.b2=0.95",
        "optim.weight_decay=0.05",
    ]
    if nu_dtype:
        out.append(f"optim.nu_dtype={nu_dtype}")
    return out
