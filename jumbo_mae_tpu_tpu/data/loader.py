"""Streaming dataloader: shards → decode → augment → batches → device.

The TPU-native replacement for the reference's webdataset + torch DataLoader
stack (``/root/reference/src/dataset.py:100-161``). Same external contracts:

- train: infinite stream, deterministic shard order shuffle per epoch,
  per-process striping, per-worker split, streaming sample shuffle,
  repeated augmentation with clones de-interleaved across the batch
  (``collate_and_shuffle``, ``/root/reference/src/dataset.py:85-92``);
- valid: one sequential pass, final partial batch padded to full size with
  ``valid=False`` rows and ``label=-1`` (the reference's ``-1``-pad contract,
  ``/root/reference/src/dataset.py:95-97``), so every process issues the same
  number of identically-shaped steps;
- batches are host numpy uint8 NHWC; normalization runs on device.

Differences by design: workers are ``multiprocessing`` processes owned by
this module (no torch), every worker's stream is reproducible from (seed,
process_index, worker_index, epoch), and batches land on device through a
double-buffered ``jax.device_put`` with an explicit ``NamedSharding`` so
host→device copy overlaps compute (the reference relied on pmap's implicit
transfer with no overlap).
"""

from __future__ import annotations

import queue as queue_mod
import time
from collections.abc import Iterator
from dataclasses import dataclass
from pathlib import Path

import numpy as np

from jumbo_mae_tpu_tpu.data.decode import decode_image, decode_label, find_image_key
from jumbo_mae_tpu_tpu.faults.inject import fault_point
from jumbo_mae_tpu_tpu.obs.metrics import get_registry
from jumbo_mae_tpu_tpu.data.randaugment import auto_augment_factory
from jumbo_mae_tpu_tpu.data.shards import expand_shards, shuffle_shards, split_shards
from jumbo_mae_tpu_tpu.data.tario import RetryPolicy, iter_shards_samples
from jumbo_mae_tpu_tpu.data.transforms import (
    color_jitter,
    eval_transform,
    random_erasing,
    random_hflip,
    random_resized_crop,
    simple_resize_crop,
)


@dataclass(frozen=True)
class DataConfig:
    """Pipeline knobs; defaults mirror the reference's argparse defaults
    (``/root/reference/src/main_finetune.py:97-160``)."""

    train_shards: str | list[str] = ""
    valid_shards: str | list[str] = ""
    image_size: int = 224
    labeled: bool = True
    crop_mode: str = "rrc"  # rrc | src | none
    min_scale: float = 0.2
    hflip: float = 0.5
    auto_augment: str = "none"
    color_jitter: float = 0.0
    random_erasing: float = 0.0
    repeats: int = 1
    shuffle_buffer: int = 1000
    test_crop_ratio: float = 0.875
    seed: int = 0
    workers: int = 4
    prefetch_batches: int = 4
    # shard-read resilience (data/tario.py): transient OSError/pipe failures
    # get shard_retries attempts with capped exponential backoff (base
    # shard_retry_backoff_s, jittered) before the shard is quarantined for
    # the rest of the epoch pass (counted + surfaced in /healthz)
    shard_retries: int = 3
    shard_retry_backoff_s: float = 0.05
    # samples per epoch — used only to convert a resumed step count into the
    # stream's starting epoch (coarse data-cursor resume)
    dataset_size: int = 1_281_167
    # use the native C++ threaded tar reader (native/tario.cc) as the IO
    # substrate instead of per-worker Python tarfile streams
    use_native: bool = False
    native_io_threads: int = 4
    decode_threads: int = 4
    # directory for the on-disk validation-sample cache (data/valcache.py):
    # the first eval pass writes post-transform tensors there, every later
    # eval streams from the cache with zero shard reads/decodes (parity+:
    # the reference cached the raw val tars, /root/reference/src/dataset.py:141).
    # Empty string disables caching.
    valid_cache: str = ""


@dataclass
class StreamCursor:
    """Mutable position of a train sample stream: ``offset`` samples (clones
    included) have been yielded within ``epoch``. Updated in place by the
    stream generators after every yield, so whoever drains the stream can
    snapshot an exact resume point (sample-exact resume — beyond the
    reference, whose restart lost the data position entirely,
    ``/root/reference/src/utils.py:55-63``)."""

    epoch: int = 0
    offset: int = 0


def _retry_policy(cfg: DataConfig) -> RetryPolicy:
    """The shard-read retry policy every stream in this module uses."""
    return RetryPolicy(
        attempts=max(1, cfg.shard_retries),
        backoff_s=max(0.0, cfg.shard_retry_backoff_s),
    )


def _aug_rng(
    seed: int, process_index: int, worker_index: int, epoch: int, idx: int
) -> np.random.Generator:
    """Per-sample augmentation RNG, derived independently of the shuffle RNG.

    Keying augmentation on the yielded-sample index (instead of sharing the
    epoch stream's generator) is what makes fast-skip possible: a resumed
    stream can skip the transform compute for already-consumed samples
    without perturbing any RNG state the remaining samples depend on.
    """
    return np.random.default_rng((seed, 3, process_index, worker_index, epoch, idx))


class TrainTransform:
    """Per-sample train augmentation chain (crop → flip → policy → jitter →
    erasing), reproducing ``create_transforms`` train branch
    (``/root/reference/src/dataset.py:56-75``)."""

    def __init__(self, cfg: DataConfig):
        self.cfg = cfg
        self.policy = auto_augment_factory(cfg.auto_augment)

    def __call__(self, rng: np.random.Generator, img: np.ndarray) -> np.ndarray:
        cfg = self.cfg
        if cfg.crop_mode == "rrc":
            img = random_resized_crop(
                rng, img, cfg.image_size, scale=(cfg.min_scale, 1.0)
            )
        elif cfg.crop_mode == "src":
            img = simple_resize_crop(rng, img, cfg.image_size)
        else:
            from jumbo_mae_tpu_tpu.data.transforms import resize

            img = resize(img, (cfg.image_size, cfg.image_size))
        img = random_hflip(rng, img, cfg.hflip)
        if self.policy is not None:
            img = self.policy(rng, img)
        if cfg.color_jitter > 0:
            img = color_jitter(rng, img, cfg.color_jitter)
        if cfg.random_erasing > 0:
            img = random_erasing(rng, img, cfg.random_erasing)
        return np.ascontiguousarray(img)


def _shuffle_stream(
    it: Iterator, buffer_size: int, rng: np.random.Generator
) -> Iterator:
    """Streaming buffer shuffle (webdataset ``detshuffle`` equivalent)."""
    if buffer_size <= 1:
        yield from it
        return
    buf: list = []
    for x in it:
        if len(buf) < buffer_size:
            buf.append(x)
            continue
        i = int(rng.integers(len(buf)))
        buf[i], x = x, buf[i]
        yield x
    rng.shuffle(buf)  # type: ignore[arg-type]
    yield from buf


def train_sample_stream(
    cfg: DataConfig,
    *,
    process_index: int = 0,
    process_count: int = 1,
    worker_index: int = 0,
    worker_count: int = 1,
    start_epoch: int = 0,
    skip_samples: int = 0,
    cursor: StreamCursor | None = None,
    ledger=None,
    epoch_shard_override: list | None = None,
) -> Iterator[tuple[np.ndarray, int]]:
    """Infinite (image, label) stream for one (process, worker) pair.

    ``skip_samples`` fast-forwards past already-consumed samples of the
    starting epoch: shard order, shuffle-buffer draws, and decode all replay
    (they define WHICH samples come next) but the augmentation transform —
    the expensive part — is skipped, and per-sample RNG keying keeps the
    remaining stream bit-identical to an uninterrupted one.

    ``ledger`` (a :class:`~jumbo_mae_tpu_tpu.data.resize.ShardLedger`)
    tracks which epoch shards have been FULLY yielded through the shuffle
    buffer — the cursor a resized resume stripes the remainder from.
    ``epoch_shard_override`` replaces the stream's shard stripe for the
    STARTING epoch only (``(global_index, url)`` pairs from
    :func:`~jumbo_mae_tpu_tpu.data.resize.resize_assignment`); later
    epochs stripe normally at the current topology.
    """
    shards = expand_shards(cfg.train_shards)
    transform = TrainTransform(cfg)
    # per-sample decode time — in a worker subprocess this lands in that
    # process's own registry (unexported), in the inline/native path it
    # feeds the exporter directly
    reg = get_registry()
    m_decode = reg.histogram(
        "data_decode_seconds", "image decode time per sample"
    )
    m_decode_fail = reg.counter(
        "data_decode_failures_total", "samples dropped by a failed decode"
    )
    retry = _retry_policy(cfg)
    epoch = start_epoch
    to_skip = max(0, skip_samples)
    while True:
        rng = np.random.default_rng(
            (cfg.seed, 1, process_index, worker_index, epoch)
        )
        order = shuffle_shards(shards, seed=cfg.seed, epoch=epoch)
        if epoch_shard_override is not None and epoch == start_epoch:
            epoch_pairs = [(int(g), str(u)) for g, u in epoch_shard_override]
        else:
            gidx = split_shards(
                list(range(len(order))),  # type: ignore[arg-type]
                process_index=process_index,
                process_count=process_count,
                worker_index=worker_index,
                worker_count=worker_count,
            )
            epoch_pairs = [(g, order[g]) for g in gidx]

        def decoded():
            # one iter_shards_samples call per shard (instead of one for
            # the whole stripe) so the ledger sees shard boundaries; retry
            # and quarantine are per-shard in tario, so behavior is
            # unchanged
            for g, url in epoch_pairs:
                for sample in iter_shards_samples([url], retry=retry):
                    img_key = find_image_key(sample)
                    if img_key is None:
                        continue
                    t0 = time.perf_counter()
                    payload = fault_point(
                        "data.decode",
                        key=str(sample.get("__key__", "")),
                        data=sample[img_key],
                    )
                    img = decode_image(payload)  # type: ignore[arg-type]
                    m_decode.observe(time.perf_counter() - t0)
                    if img is None:
                        m_decode_fail.inc()
                        continue
                    label = decode_label(sample["cls"]) if "cls" in sample else -1
                    if ledger is not None:
                        ledger.note_read(epoch, g)
                    yield g, (img, label)
                if ledger is not None:
                    ledger.note_read_done(epoch, g)

        idx = 0
        for g, (img, label) in _shuffle_stream(decoded(), cfg.shuffle_buffer, rng):
            if ledger is not None:
                ledger.note_yield(epoch, g)
            for _ in range(cfg.repeats):
                if to_skip > 0:
                    to_skip -= 1
                    idx += 1
                    continue
                aug = _aug_rng(cfg.seed, process_index, worker_index, epoch, idx)
                out = transform(aug, img), label
                idx += 1
                if cursor is not None:
                    cursor.epoch, cursor.offset = epoch, idx
                yield out
        epoch += 1


def valid_sample_stream(
    cfg: DataConfig, *, process_index: int = 0, process_count: int = 1
) -> Iterator[tuple[np.ndarray, int]]:
    """One sequential eval pass over this process's stripe of the valid set."""
    shards = split_shards(
        expand_shards(cfg.valid_shards),
        process_index=process_index,
        process_count=process_count,
    )
    for sample in iter_shards_samples(shards, retry=_retry_policy(cfg)):
        img_key = find_image_key(sample)
        if img_key is None:
            continue
        img = decode_image(sample[img_key])  # type: ignore[arg-type]
        if img is None:
            continue
        label = decode_label(sample["cls"]) if "cls" in sample else -1
        yield eval_transform(img, cfg.image_size, crop_ratio=cfg.test_crop_ratio), label


def native_train_stream(
    cfg: DataConfig,
    *,
    process_index: int = 0,
    process_count: int = 1,
    start_epoch: int = 0,
    skip_samples: int = 0,
    cursor: StreamCursor | None = None,
) -> Iterator[tuple[np.ndarray, int]]:
    """Native-IO train stream: C++ reader threads feed raw image bytes, a
    thread pool does decode+augment (cv2/PIL release the GIL, so this scales
    within one process where the pure-Python path needs worker processes).

    One epoch of the process's shard stripe per native reader; shard order is
    reshuffled per epoch like :func:`train_sample_stream`. SAMPLE-EXACTLY
    RESUMABLE: the C++ reader gives each thread static ownership of every
    T-th shard and merges thread queues in strict round-robin
    (``native/tario.cc``), so the sample order is a pure function of
    (shard list, ``native_io_threads``) and ``skip_samples`` replays the
    consumed prefix exactly, same contract as :func:`train_sample_stream`
    (decode and shuffle-buffer draws replay; the augmentation transform is
    skipped).
    """
    from concurrent.futures import ThreadPoolExecutor

    from jumbo_mae_tpu_tpu.data.native import NativeShardReader

    shards = expand_shards(cfg.train_shards)
    transform = TrainTransform(cfg)
    reg = get_registry()
    m_decode = reg.histogram(
        "data_decode_seconds", "image decode time per sample"
    )
    m_decode_fail = reg.counter(
        "data_decode_failures_total", "samples dropped by a failed decode"
    )
    epoch = start_epoch
    to_skip = max(0, skip_samples)
    with ThreadPoolExecutor(max_workers=max(1, cfg.decode_threads)) as pool:
        while True:
            rng = np.random.default_rng((cfg.seed, 2, process_index, epoch))
            epoch_shards = split_shards(
                shuffle_shards(shards, seed=cfg.seed, epoch=epoch),
                process_index=process_index,
                process_count=process_count,
            )

            def decode_one(pair):
                payload, label = pair
                t0 = time.perf_counter()
                payload = fault_point("data.decode", data=payload)
                img = decode_image(payload)
                m_decode.observe(time.perf_counter() - t0)
                if img is None:
                    m_decode_fail.inc()
                    return None
                return (img, label)

            def decoded(reader):
                # bounded in-flight futures (NOT pool.map, which eagerly
                # drains the whole reader and buffers an epoch of JPEGs):
                # the window is what keeps backpressure on the C++ queue
                from collections import deque

                window: deque = deque()
                depth = max(2, cfg.decode_threads * 4)
                for pair in reader:
                    window.append(pool.submit(decode_one, pair))
                    if len(window) >= depth:
                        r = window.popleft().result()
                        if r is not None:
                            yield r
                while window:
                    r = window.popleft().result()
                    if r is not None:
                        yield r

            with NativeShardReader(
                epoch_shards, threads=cfg.native_io_threads, loop=False
            ) as reader:
                idx = 0
                for img, label in _shuffle_stream(
                    decoded(reader), cfg.shuffle_buffer, rng
                ):
                    for _ in range(cfg.repeats):
                        if to_skip > 0:
                            to_skip -= 1
                            idx += 1
                            continue
                        aug = _aug_rng(cfg.seed, process_index, 0, epoch, idx)
                        out = transform(aug, img), label
                        idx += 1
                        if cursor is not None:
                            cursor.epoch, cursor.offset = epoch, idx
                        yield out
            epoch += 1


def _deinterleave(indices: int, repeats: int) -> np.ndarray:
    """Batch reorder that spreads repeated-augmentation clones across the
    batch: position j ← sample j*repeats % n adjusted — equivalent to the
    reference's ``batch[i::repeats]`` concatenation
    (``/root/reference/src/dataset.py:91-92``)."""
    order = np.arange(indices)
    return np.concatenate([order[i::repeats] for i in range(repeats)])


def batch_train_samples(
    stream: Iterator[tuple[np.ndarray, int]],
    batch_size: int,
    repeats: int = 1,
    cursor: StreamCursor | None = None,
    ledger=None,
) -> Iterator[dict[str, np.ndarray]]:
    """Assemble train batches; de-interleave repeat clones. With ``cursor``
    (the SAME object the stream updates), each batch carries a ``_cursor``
    key — the (epoch, offset) reached after its last sample — so consumers
    can checkpoint a sample-exact resume point. With ``ledger`` (the SAME
    object the stream updates), each batch also carries a ``_shards`` key —
    the consumed-shard snapshot as of its last sample — for resize-safe
    elastic resume."""
    order = _deinterleave(batch_size, max(1, repeats))
    while True:
        pairs = [next(stream) for _ in range(batch_size)]
        images = np.stack([p[0] for p in pairs])[order]
        labels = np.asarray([p[1] for p in pairs], np.int32)[order]
        batch = {"images": images, "labels": labels}
        if cursor is not None:
            batch["_cursor"] = (cursor.epoch, cursor.offset)
        if ledger is not None:
            batch["_shards"] = ledger.snapshot()
        yield batch


def batch_valid_samples(
    stream: Iterator[tuple[np.ndarray, int]],
    batch_size: int,
    image_size: int,
) -> Iterator[dict[str, np.ndarray]]:
    """Assemble eval batches; pad the final partial batch (valid=False,
    label=-1) so step shapes stay constant."""
    images = np.zeros((batch_size, image_size, image_size, 3), np.uint8)
    labels = np.full((batch_size,), -1, np.int32)
    valid = np.zeros((batch_size,), bool)
    n = 0
    for img, label in stream:
        images[n], labels[n], valid[n] = img, label, True
        n += 1
        if n == batch_size:
            yield {"images": images.copy(), "labels": labels.copy(), "valid": valid.copy()}
            images = np.zeros_like(images)
            labels = np.full_like(labels, -1)
            valid = np.zeros_like(valid)
            n = 0
    if n:
        yield {"images": images, "labels": labels, "valid": valid}


class _Worker:
    """One data-worker subprocess + its pipe-reader thread and batch queue.

    The worker is a FRESH interpreter (``python -m
    jumbo_mae_tpu_tpu.data._worker``), not a multiprocessing child — see
    ``data/_worker.py`` for why (spawn re-imports the user's __main__; fork
    duplicates a live multithreaded XLA runtime). The reader thread turns the
    stdout frame stream into a bounded queue; EOF marks the worker dead so
    the consumer can skip it instead of hanging.
    """

    def __init__(self, spec: dict, queue_size: int):
        import json
        import os
        import subprocess
        import sys
        import threading

        from jumbo_mae_tpu_tpu.utils.procenv import cpu_subprocess_env

        # workers never use jax, and a wedged accelerator tunnel must never
        # be able to touch their startup (see utils/procenv.py)
        env = cpu_subprocess_env()
        repo_root = str(Path(__file__).resolve().parent.parent.parent)
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "jumbo_mae_tpu_tpu.data._worker", json.dumps(spec)],
            stdout=subprocess.PIPE,
            env=env,
        )
        self.queue: queue_mod.Queue = queue_mod.Queue(maxsize=queue_size)
        self.dead = False
        self._thread = threading.Thread(target=self._read_loop, daemon=True)
        self._thread.start()

    def _read_loop(self):
        import pickle
        import struct

        stream = self.proc.stdout
        try:
            while True:
                header = stream.read(8)
                if len(header) < 8:
                    break
                (length,) = struct.unpack(">Q", header)
                payload = stream.read(length)
                if len(payload) < length:
                    break
                self.queue.put(pickle.loads(payload))
        except (OSError, ValueError):  # pragma: no cover - pipe torn down
            pass
        finally:
            self.dead = True

    def stop(self):
        self.dead = True
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5)
            except Exception:  # noqa: BLE001  # pragma: no cover
                self.proc.kill()
        if self.proc.stdout is not None:
            self.proc.stdout.close()


class TrainLoader:
    """Infinite train-batch iterator backed by worker subprocesses.

    Each worker owns a disjoint shard stripe and yields WHOLE per-process
    batches (the torch IterableDataset-per-worker batching the reference
    inherited); the parent consumes worker queues in STRICT round-robin order
    — batch n always comes from worker ``n % workers`` — so the global batch
    sequence is a pure function of the config, which is what makes
    sample-exact resume possible. ``workers=0`` runs inline — the mode tests
    and CPU smoke configs use.

    ``snapshot()`` returns a JSON-able cursor (per-worker stream positions +
    the round-robin phase); constructing a loader with ``cursor=`` resumes
    the exact batch sequence from that point.
    """

    def __init__(
        self,
        cfg: DataConfig,
        batch_size: int,
        *,
        process_index: int = 0,
        process_count: int = 1,
        start_epoch: int = 0,
        cursor: dict | None = None,
        epoch_shard_override: list | None = None,
        shard_preconsumed: dict | None = None,
    ):
        if batch_size % max(1, cfg.repeats):
            raise ValueError(
                f"repeats ({cfg.repeats}) must divide the per-process batch "
                f"size ({batch_size})"
            )
        self.cfg = cfg
        self.batch_size = batch_size
        self._workers: list[_Worker] = []
        self._shard_states: list = []
        # epoch the active epoch_shard_override applies to — stamped into
        # snapshots while any stream is still inside it, so a same-world
        # restart knows the sample cursor was measured on the override
        # stripe (not the topology stripe) and must re-derive it
        self._override_epoch: int | None = None
        # loader telemetry (obs/metrics.py): how long the train loop waits
        # for batches, and whether workers are stalling or dying under it
        reg = get_registry()
        self._m_wait = reg.histogram(
            "data_batch_wait_seconds", "host wait in TrainLoader.__next__"
        )
        self._m_batches = reg.counter(
            "data_batches_total", "train batches yielded"
        )
        self._m_stalls = reg.counter(
            "data_worker_stalls_total",
            "5 s waits on an alive worker's empty queue",
            labels=("worker",),
        )
        self._m_deaths = reg.counter(
            "data_worker_deaths_total", "workers found dead at read time"
        )
        if cfg.use_native:
            # the C++ reader's deterministic per-thread shard ownership +
            # round-robin merge makes this stream a pure function of
            # (config, native_io_threads) — but only for the SAME thread
            # count, so a cursor records it and resume validates it
            if epoch_shard_override is not None:
                raise ValueError(
                    "resize-consistent resume (epoch_shard_override) is not "
                    "supported by the native-IO loader — the reader merges "
                    "per-thread queues without shard-boundary accounting; "
                    "restart with data.use_native=false or fall back to "
                    "epoch resume"
                )
            if cursor is not None:
                saved_threads = cursor.get("native_threads")
                if saved_threads is None:
                    raise ValueError(
                        "resume cursor was written by the subprocess-worker "
                        "loader (different sample order); restart with "
                        "data.use_native=false or fall back to epoch resume"
                    )
                if saved_threads != cfg.native_io_threads:
                    raise ValueError(
                        f"resume cursor was written with native_io_threads="
                        f"{saved_threads} but the loader is configured with "
                        f"{cfg.native_io_threads} — the merged sample order "
                        "differs; restart with the checkpointed thread count"
                    )
                (start, skip) = tuple(cursor["workers"][0])
                self.batches_yielded = int(cursor["batches"])
            else:
                start, skip = start_epoch, 0
                self.batches_yielded = 0
            self._native_threads = cfg.native_io_threads
            self._cursors = [(start, skip)]
            track = StreamCursor(start, skip)
            self._stream = native_train_stream(
                cfg,
                process_index=process_index,
                process_count=process_count,
                start_epoch=start,
                skip_samples=skip,
                cursor=track,
            )
            self._inline = batch_train_samples(
                self._stream, batch_size, cfg.repeats, cursor=track
            )
            return
        n_streams = 1 if cfg.workers <= 0 else cfg.workers
        if cursor is not None:
            if cursor.get("native_threads") is not None:
                raise ValueError(
                    "resume cursor was written by the native-IO loader "
                    "(round-robin-over-threads sample order); restart with "
                    "data.use_native=true or fall back to epoch resume"
                )
            starts = [tuple(c) for c in cursor["workers"]]
            if len(starts) != n_streams:
                raise ValueError(
                    f"resume cursor has {len(starts)} worker streams but the "
                    f"loader is configured for {n_streams} — restart with the "
                    f"checkpointed worker count or fall back to epoch resume"
                )
            self.batches_yielded = int(cursor["batches"])
        else:
            starts = [(start_epoch, 0)] * n_streams
            self.batches_yielded = 0
        self._cursors = list(starts)
        self._shard_states = [None] * n_streams
        if epoch_shard_override is not None:
            self._override_epoch = min(e for e, _ in starts)
        if cfg.workers <= 0:
            from jumbo_mae_tpu_tpu.data.resize import ShardLedger

            led = ShardLedger(preconsumed=shard_preconsumed)
            track = StreamCursor(*starts[0])
            self._stream = train_sample_stream(
                cfg,
                process_index=process_index,
                process_count=process_count,
                start_epoch=starts[0][0],
                skip_samples=starts[0][1],
                cursor=track,
                ledger=led,
                epoch_shard_override=epoch_shard_override,
            )
            self._inline = batch_train_samples(
                self._stream, batch_size, cfg.repeats, cursor=track, ledger=led
            )
            return
        self._inline = None
        from dataclasses import asdict

        per_worker_q = max(1, cfg.prefetch_batches // cfg.workers)
        for w in range(cfg.workers):
            spec = {
                "data": asdict(cfg),
                "batch_size": batch_size,
                "process_index": process_index,
                "process_count": process_count,
                "worker_index": w,
                "worker_count": cfg.workers,
                "start_epoch": starts[w][0],
                "skip_samples": starts[w][1],
            }
            if epoch_shard_override is not None:
                # worker w owns every W-th pair of the process's remainder
                # stripe — same [w::W] discipline as split_shards
                spec["epoch_shard_override"] = [
                    [int(g), str(u)]
                    for g, u in epoch_shard_override[w :: cfg.workers]
                ]
            if shard_preconsumed is not None:
                spec["shard_preconsumed"] = shard_preconsumed
            self._workers.append(_Worker(spec, per_worker_q))

    def snapshot(self) -> dict | None:
        """Resume cursor as of the last batch returned by ``__next__``.
        Native-IO snapshots also record the reader thread count — the
        deterministic merge order depends on it, so resume validates it.
        While any stream is still inside an active ``epoch_shard_override``
        epoch, the snapshot carries ``override_epoch``: its offsets were
        measured against the override stripe, so a restart — even at the
        SAME world size — must re-derive the override from the journaled
        shard cursors instead of replaying the offsets on the topology
        stripe. Once every stream has crossed into a later (normally
        striped) epoch, the marker drops off and sample-exact resume is
        valid again."""
        if not self._cursors:
            return None
        snap = {
            "workers": [list(c) for c in self._cursors],
            "batches": self.batches_yielded,
        }
        if getattr(self, "_native_threads", None) is not None:
            snap["native_threads"] = self._native_threads
        if self._override_epoch is not None and any(
            e <= self._override_epoch for e, _ in self._cursors
        ):
            snap["override_epoch"] = self._override_epoch
        return snap

    def shard_snapshot(self) -> dict | None:
        """Merged consumed-shard state across this process's streams, as of
        the last batch returned by ``__next__`` — the per-host payload of
        the ``shard_cursor`` journal event a resized resume reads. ``None``
        on the native path (no shard-boundary accounting)."""
        if not self._shard_states:
            return None
        from jumbo_mae_tpu_tpu.data.resize import merge_shard_states

        merged = merge_shard_states(self._shard_states)
        return {"epochs": {str(e): sorted(v) for e, v in merged.items()}}

    def __iter__(self):
        return self

    def __next__(self) -> dict[str, np.ndarray]:
        t_wait = time.perf_counter()
        if self._inline is not None:
            batch = next(self._inline)
            slot = 0
        else:
            slot = self.batches_yielded % len(self._workers)
            w = self._workers[slot]
            attempts_left = 120  # x 5s = 10 min of silence before giving up
            while True:
                if w.dead and w.queue.empty():
                    # skipping a dead worker would silently fork the batch
                    # sequence away from the deterministic schedule
                    self._m_deaths.inc()
                    raise RuntimeError(
                        f"data worker {slot} died; deterministic stream lost"
                    )
                try:
                    batch = w.queue.get(timeout=5)
                    break
                except queue_mod.Empty:
                    self._m_stalls.labels(str(slot)).inc()
                    attempts_left -= 1
                    if attempts_left <= 0:
                        raise RuntimeError(
                            f"data worker {slot} alive but produced nothing "
                            "for 10 minutes"
                        ) from None
        self._m_wait.observe(time.perf_counter() - t_wait)
        self._m_batches.inc()
        cur = batch.pop("_cursor", None)
        if cur is not None:
            self._cursors[slot] = (int(cur[0]), int(cur[1]))
        sh = batch.pop("_shards", None)
        if sh is not None and self._shard_states:
            self._shard_states[slot] = sh
        self.batches_yielded += 1
        return batch

    def close(self):
        for w in self._workers:
            w.stop()
        self._workers.clear()
        # close inline generators now (innermost first) so stream resources
        # (native reader threads, decode pools) unwind while the interpreter
        # is still fully alive, not at GC-at-exit time
        if getattr(self, "_inline", None) is not None:
            self._inline.close()
            self._inline = None
        if getattr(self, "_stream", None) is not None:
            self._stream.close()
            self._stream = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass


def valid_loader(
    cfg: DataConfig,
    batch_size: int,
    *,
    process_index: int = 0,
    process_count: int = 1,
) -> Iterator[dict[str, np.ndarray]]:
    """Fresh sequential eval iterator (construct per evaluation). With
    ``cfg.valid_cache`` set, the first pass populates the on-disk sample
    cache and every later pass streams from it without touching the shards."""
    if cfg.valid_cache:
        from jumbo_mae_tpu_tpu.data.valcache import ValidSampleCache

        cache = ValidSampleCache(
            cfg.valid_cache,
            key_fields={
                "shards": expand_shards(cfg.valid_shards),
                "image_size": cfg.image_size,
                "test_crop_ratio": cfg.test_crop_ratio,
                "process_index": process_index,
                "process_count": process_count,
            },
            image_size=cfg.image_size,
        )
        if cache.complete():
            stream = cache.read()
        else:
            stream = cache.capture(
                valid_sample_stream(
                    cfg, process_index=process_index, process_count=process_count
                )
            )
    else:
        stream = valid_sample_stream(
            cfg, process_index=process_index, process_count=process_count
        )
    return batch_valid_samples(stream, batch_size, cfg.image_size)


def split_for_accum(batch: dict, grad_accum: int) -> dict:
    """Reshape (B, ...) leaves to (accum, B/accum, ...) for the scan-based
    accumulation step."""
    if grad_accum <= 1:
        return batch
    return {
        k: v.reshape(grad_accum, v.shape[0] // grad_accum, *v.shape[1:])
        for k, v in batch.items()
    }


def prefetch_to_device(it: Iterator[dict], sharding, buffer_size: int = 2) -> Iterator[dict]:
    """Double-buffered host→device transfer: keep ``buffer_size`` batches in
    flight as sharded device arrays so the copy overlaps the previous step's
    compute. With a multi-process mesh, per-host batches are the local stripe
    of the global batch (``jax.make_array_from_process_local_data``)."""
    import jax

    def put(batch):
        try:
            return jax.tree_util.tree_map(
                lambda x: jax.make_array_from_process_local_data(sharding, x), batch
            )
        except ValueError:
            return jax.device_put(batch, sharding)

    pending: list = []
    for batch in it:
        pending.append(put(batch))
        if len(pending) > buffer_size:
            yield pending.pop(0)
    yield from pending
