from jumbo_mae_tpu_tpu.data.loader import (
    DataConfig,
    StreamCursor,
    TrainLoader,
    batch_train_samples,
    batch_valid_samples,
    prefetch_to_device,
    split_for_accum,
    train_sample_stream,
    valid_loader,
    valid_sample_stream,
)
from jumbo_mae_tpu_tpu.data.resize import (
    ShardLedger,
    epoch_shard_order,
    merge_shard_states,
    resize_assignment,
)
from jumbo_mae_tpu_tpu.data.shards import expand_shards, shuffle_shards, split_shards
from jumbo_mae_tpu_tpu.data.synthetic import synthetic_batches
from jumbo_mae_tpu_tpu.data.tario import (
    iter_shards_samples,
    iter_tar_samples,
    write_tar_samples,
)

__all__ = [
    "DataConfig",
    "ShardLedger",
    "StreamCursor",
    "TrainLoader",
    "batch_train_samples",
    "batch_valid_samples",
    "epoch_shard_order",
    "expand_shards",
    "iter_shards_samples",
    "merge_shard_states",
    "resize_assignment",
    "iter_tar_samples",
    "prefetch_to_device",
    "shuffle_shards",
    "split_for_accum",
    "split_shards",
    "synthetic_batches",
    "train_sample_stream",
    "valid_loader",
    "valid_sample_stream",
    "write_tar_samples",
]
