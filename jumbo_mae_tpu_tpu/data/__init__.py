from jumbo_mae_tpu_tpu.data.synthetic import synthetic_batches

__all__ = ["synthetic_batches"]
