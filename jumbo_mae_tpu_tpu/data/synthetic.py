"""Synthetic data source for smoke tests and benchmarks.

Generates deterministic uint8 image batches (and labels) host-side with
numpy — no files, no decode cost — in the same dict layout the real loader
produces: ``{"images": (B,H,W,C) uint8, "labels": (B,) int32, "valid": (B,)
bool}``.
"""

from __future__ import annotations

from collections.abc import Iterator

import numpy as np


def synthetic_batches(
    batch_size: int,
    image_size: int = 224,
    *,
    labels: int | None = None,
    grad_accum: int = 1,
    seed: int = 0,
    distinct: int = 8,
) -> Iterator[dict]:
    """Infinite iterator of synthetic batches.

    ``distinct`` controls how many unique batches are cycled (keeps host
    cost trivial while avoiding a single constant batch). With
    ``grad_accum > 1`` leaves get a leading (accum, micro, ...) shape.
    """
    rng = np.random.RandomState(seed)
    shape = (batch_size, image_size, image_size, 3)
    pool = []
    for _ in range(distinct):
        batch = {"images": rng.randint(0, 256, shape, dtype=np.uint8)}
        if labels is not None:
            batch["labels"] = rng.randint(0, labels, (batch_size,)).astype(
                np.int32
            )
        batch["valid"] = np.ones((batch_size,), bool)
        if grad_accum > 1:
            if batch_size % grad_accum:
                raise ValueError("batch_size must divide by grad_accum")
            batch = {
                k: v.reshape(grad_accum, batch_size // grad_accum, *v.shape[1:])
                for k, v in batch.items()
            }
        pool.append(batch)
    i = 0
    while True:
        yield pool[i % distinct]
        i += 1
