"""Resize-consistent shard assignment for elastic training.

When the :class:`~jumbo_mae_tpu_tpu.train.elastic.ElasticSupervisor`
relaunches a run at a different world size, the sample-exact cursor in the
checkpoint is useless: per-worker offsets describe streams striped for the
OLD ``(process_count, worker_count)`` topology, and replaying them under a
new one would re-read some shards and never read others. This module makes
the post-resize assignment a pure function of
``(world_size, process_id, journal cursor)``:

- every host journals a ``shard_cursor`` event at each checkpoint — the
  set of epoch-shard indices its streams have FULLY consumed as of that
  step (:class:`ShardLedger` tracks this exactly through the shuffle
  buffer);
- at a resized resume, the union of all old hosts' consumed sets is
  subtracted from the epoch's deterministic shard order, and the remainder
  is striped across the new world (:func:`resize_assignment`).

Guarantees (pinned by ``tests/test_elastic.py``): across the resize, the
union of shards consumed before the checkpoint and shards assigned after
it covers every shard of the epoch exactly once — no shard double-counted,
none skipped. Granularity is the SHARD: a shard that was only partially
consumed at the checkpointed step is replayed from its first sample (those
samples carry no surviving gradient in the rewound weights, so replay is
correct, not double-counting).

Shard identity is the GLOBAL INDEX into the epoch's deterministic shuffled
order (``shuffle_shards(expand_shards(spec), seed, epoch)``) — a portable
integer every process computes identically without communicating, which is
what lets per-host journals act as the cursor with no collective.
"""

from __future__ import annotations

from jumbo_mae_tpu_tpu.data.shards import expand_shards, shuffle_shards


class ShardLedger:
    """Per-stream ledger of fully-consumed epoch shards.

    "Consumed" means every decoded sample of the shard has been YIELDED
    downstream — not merely read into the shuffle buffer. The stream calls
    :meth:`note_read` as each decoded sample enters the buffer,
    :meth:`note_read_done` when the shard's tar iteration finishes, and
    :meth:`note_yield` as each sample exits the buffer; a shard is
    promoted to ``consumed`` when its reads are done and every read sample
    has been yielded. A shard quarantined by the tar reader mid-epoch
    promotes like any other (matching the non-elastic one-pass-per-epoch
    behavior: a quarantined shard is not retried until the next epoch).

    Thread-compat: each stream owns its private ledger (one per
    (process, worker) pair); no locking needed.

    ``preconsumed`` seeds the ledger with shards consumed by EARLIER
    generations (the merged set a resized resume subtracted when it built
    the stream's ``epoch_shard_override``). Without the seed, a fresh
    generation's ``shard_cursor`` snapshots would record only its own
    consumption, and the NEXT resize would re-assign everything consumed
    before the first one — snapshots must stay cumulative across
    generations for the conservation invariant to survive repeated
    world-size changes. Accepts the :meth:`snapshot` shape
    (``{"epochs": {str(epoch): [gidx, ...]}}``) or a bare
    ``{epoch: indices}`` mapping.
    """

    def __init__(self, preconsumed: dict | None = None):
        self._reads: dict[tuple[int, int], int] = {}
        self._yields: dict[tuple[int, int], int] = {}
        self._read_done: set[tuple[int, int]] = set()
        #: epoch -> sorted list of fully-consumed global shard indices
        self.consumed: dict[int, list[int]] = {}
        if preconsumed:
            epochs = preconsumed.get("epochs", preconsumed)
            for e, idxs in epochs.items():
                self.consumed[int(e)] = sorted(int(i) for i in idxs)

    def note_read(self, epoch: int, gidx: int) -> None:
        k = (epoch, gidx)
        self._reads[k] = self._reads.get(k, 0) + 1

    def note_read_done(self, epoch: int, gidx: int) -> None:
        k = (epoch, gidx)
        self._read_done.add(k)
        self._maybe_promote(k)

    def note_yield(self, epoch: int, gidx: int) -> None:
        k = (epoch, gidx)
        self._yields[k] = self._yields.get(k, 0) + 1
        self._maybe_promote(k)

    def _maybe_promote(self, k: tuple[int, int]) -> None:
        if k in self._read_done and self._yields.get(k, 0) >= self._reads.get(k, 0):
            epoch, gidx = k
            lst = self.consumed.setdefault(epoch, [])
            if gidx not in lst:
                lst.append(gidx)
                lst.sort()
            # retire the counters — the shard is settled
            self._read_done.discard(k)
            self._reads.pop(k, None)
            self._yields.pop(k, None)

    def snapshot(self) -> dict:
        """JSON-able state: ``{"epochs": {str(epoch): [gidx, ...]}}``."""
        return {"epochs": {str(e): list(v) for e, v in self.consumed.items()}}


def merge_shard_states(states) -> dict[int, set[int]]:
    """Union per-epoch consumed sets across ledger snapshots (one per old
    (process, worker) stream / host). ``None`` entries are skipped."""
    out: dict[int, set[int]] = {}
    for st in states:
        if not st:
            continue
        for e, idxs in (st.get("epochs") or {}).items():
            out.setdefault(int(e), set()).update(int(i) for i in idxs)
    return out


def epoch_shard_order(
    train_shards: str | list[str], *, seed: int, epoch: int
) -> list[str]:
    """The epoch's deterministic global shard order — identical on every
    process; the namespace the ledger's global indices live in."""
    return shuffle_shards(expand_shards(train_shards), seed=seed, epoch=epoch)


def resize_assignment(
    order: list[str],
    consumed,
    *,
    world_size: int,
    process_id: int,
    worker_index: int = 0,
    worker_count: int = 1,
) -> list[tuple[int, str]]:
    """Stripe the epoch's un-consumed remainder across the new world.

    Pure function of ``(world_size, process_id, cursor)``: ``order`` is
    the epoch's deterministic shard order, ``consumed`` the union of
    global indices fully consumed before the checkpointed step. Returns
    ``(global_index, url)`` pairs for one (process, worker) stream —
    order-preserving striping, same ``[p::N][w::W]`` discipline as
    :func:`~jumbo_mae_tpu_tpu.data.shards.split_shards`, so the union over
    all new (process, worker) pairs is exactly the remainder, disjointly.
    """
    if not 0 <= process_id < world_size:
        raise ValueError(f"bad process {process_id}/{world_size}")
    if not 0 <= worker_index < worker_count:
        raise ValueError(f"bad worker {worker_index}/{worker_count}")
    gone = {int(i) for i in consumed}
    bad = [i for i in gone if not 0 <= i < len(order)]
    if bad:
        raise ValueError(f"consumed indices out of range: {sorted(bad)[:5]}")
    remaining = [(i, u) for i, u in enumerate(order) if i not in gone]
    return remaining[process_id::world_size][worker_index::worker_count]
