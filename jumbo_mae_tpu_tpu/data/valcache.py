"""On-disk validation-sample cache: evals after the first do zero shard IO.

Reference parity: ``cached_tarfile_to_samples()``
(``/root/reference/src/dataset.py:141``) kept the downloaded validation tars
on local disk so repeat evals hit disk instead of the network. This goes one
step further for the TPU-native stack: the cache stores the POST-transform
eval tensors (resize + center-crop already applied, fixed uint8 shape), so
every eval after the first skips shard reads, JPEG decode, AND resize — it
streams straight out of one memory-mapped flat file.

Layout (under the configured cache directory, keyed by a hash of everything
that determines the stream: shard list, image size, crop ratio, and this
process's stripe):

    val-<key>.bin    images, n × (S, S, 3) uint8, append-written
    val-<key>.json   labels + sample count + the key fields (echoed for
                     humans); written LAST, so its presence is the commit
                     marker — a crash mid-capture leaves only a .tmp that the
                     next pass overwrites.
"""

from __future__ import annotations

import hashlib
import json
import os
import uuid
from collections.abc import Iterator
from pathlib import Path

import numpy as np

Sample = tuple[np.ndarray, int]


class ValidSampleCache:
    """Write-once, read-many cache of one process's eval-sample stream."""

    def __init__(self, directory: str, key_fields: dict, image_size: int):
        self.image_size = int(image_size)
        self.key_fields = dict(key_fields)
        blob = json.dumps(self.key_fields, sort_keys=True, default=str)
        key = hashlib.sha1(blob.encode()).hexdigest()[:16]
        root = Path(directory)
        root.mkdir(parents=True, exist_ok=True)
        self.data_path = root / f"val-{key}.bin"
        self.meta_path = root / f"val-{key}.json"

    def complete(self) -> bool:
        """True when a committed cache for these key fields exists."""
        if not (self.meta_path.is_file() and self.data_path.is_file()):
            return False
        try:
            meta = json.loads(self.meta_path.read_text())
        except (OSError, ValueError):
            return False
        if meta.get("key_fields") != json.loads(
            json.dumps(self.key_fields, default=str)
        ):
            return False
        expect = meta["count"] * self.image_size * self.image_size * 3
        return self.data_path.stat().st_size == expect

    def capture(self, stream: Iterator[Sample]) -> Iterator[Sample]:
        """Pass ``stream`` through while writing it to the cache; the cache
        commits only if the stream is drained to the end."""
        # unique per writer: concurrent jobs sharing a cache dir must not
        # interleave into one tmp file (the atomic replace only isolates
        # writers if each writes its own file; last committer wins)
        tmp = self.data_path.with_suffix(f".bin.{os.getpid()}.{uuid.uuid4().hex[:8]}.tmp")
        labels: list[int] = []
        finished = False
        try:
            with open(tmp, "wb") as f:
                for img, label in stream:
                    f.write(np.ascontiguousarray(img, np.uint8).tobytes())
                    labels.append(int(label))
                    yield img, label
            finished = True
        finally:
            if finished:
                tmp.replace(self.data_path)
                # atomic like the .bin commit: a racing reader must never
                # see a truncated JSON
                meta_tmp = self.meta_path.with_suffix(".json.tmp")
                meta_tmp.write_text(
                    json.dumps(
                        {
                            "count": len(labels),
                            "labels": labels,
                            "key_fields": self.key_fields,
                        },
                        default=str,
                    )
                )
                meta_tmp.replace(self.meta_path)
            else:
                tmp.unlink(missing_ok=True)

    def read(self) -> Iterator[Sample]:
        """Stream samples back from the committed cache (memory-mapped; no
        shard IO, no decode)."""
        meta = json.loads(self.meta_path.read_text())
        n, s = meta["count"], self.image_size
        if n == 0:  # np.memmap cannot map an empty file; an empty stripe
            return  # (process_count > shards) is a legal committed cache
        images = np.memmap(self.data_path, np.uint8, mode="r", shape=(n, s, s, 3))
        for i, label in enumerate(meta["labels"]):
            yield images[i], int(label)
