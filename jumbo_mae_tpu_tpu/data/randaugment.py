"""Auto-augmentation policies: RandAugment / AugMix / AutoAugment.

A from-scratch port of the timm policy family the reference selected by
prefix string (``/root/reference/src/dataset.py:41-53``): ``rand-...`` →
RandAugment, ``augmix-...`` → AugMix, anything else → AutoAugment. Policy
strings use the same grammar (``rand-m9-mstd0.5-inc1``,
``augmix-m3-w3-d2``, ``original``), so the reference's recipe flags
(``--auto-augment rand-m9-mstd0.5-inc1`` in ``/root/reference/config/ft.sh``)
carry over verbatim.

Ops run on PIL images (same backend timm used, so the pixel semantics of
equalize/posterize/shear match), wrapped in a numpy-in/numpy-out API with an
explicit ``np.random.Generator``.
"""

from __future__ import annotations

import re

import numpy as np
from PIL import Image, ImageEnhance, ImageOps

_FILL = (128, 128, 128)
_MAX_LEVEL = 10.0


# ---------------------------------------------------------------- primitive ops
def _auto_contrast(img, *_):
    return ImageOps.autocontrast(img)


def _equalize(img, *_):
    return ImageOps.equalize(img)


def _invert(img, *_):
    return ImageOps.invert(img)


def _rotate(img, deg):
    return img.rotate(deg, resample=Image.BILINEAR, fillcolor=_FILL)


def _posterize(img, bits):
    return ImageOps.posterize(img, max(1, int(bits)))


def _solarize(img, thresh):
    return ImageOps.solarize(img, int(thresh))


def _solarize_add(img, add, thresh=128):
    arr = np.asarray(img).astype(np.int32)
    arr = np.where(arr < thresh, np.clip(arr + int(add), 0, 255), arr)
    return Image.fromarray(arr.astype(np.uint8))


def _color(img, factor):
    return ImageEnhance.Color(img).enhance(factor)


def _contrast(img, factor):
    return ImageEnhance.Contrast(img).enhance(factor)


def _brightness(img, factor):
    return ImageEnhance.Brightness(img).enhance(factor)


def _sharpness(img, factor):
    return ImageEnhance.Sharpness(img).enhance(factor)


def _shear_x(img, v):
    return img.transform(
        img.size, Image.AFFINE, (1, v, 0, 0, 1, 0), resample=Image.BILINEAR, fillcolor=_FILL
    )


def _shear_y(img, v):
    return img.transform(
        img.size, Image.AFFINE, (1, 0, 0, v, 1, 0), resample=Image.BILINEAR, fillcolor=_FILL
    )


def _translate_x_rel(img, pct):
    return img.transform(
        img.size,
        Image.AFFINE,
        (1, 0, pct * img.size[0], 0, 1, 0),
        resample=Image.BILINEAR,
        fillcolor=_FILL,
    )


def _translate_y_rel(img, pct):
    return img.transform(
        img.size,
        Image.AFFINE,
        (1, 0, 0, 0, 1, pct * img.size[1]),
        resample=Image.BILINEAR,
        fillcolor=_FILL,
    )


# ------------------------------------------------------------ level → op args
def _signed(rng, v):
    return -v if rng.random() < 0.5 else v


def _enhance_increasing(rng, level):
    return 1.0 + _signed(rng, (level / _MAX_LEVEL) * 0.9)


def _enhance_plain(rng, level):
    # non-"inc" variant: U-shaped range [0.1, 1.9]
    return max(0.1, (level / _MAX_LEVEL) * 1.8 + 0.1)


# name → (fn, level_to_arg(rng, level, increasing) | None)
def _level_args(name: str, rng, level: float, increasing: bool):
    if name in ("AutoContrast", "Equalize", "Invert"):
        return ()
    if name == "Rotate":
        return (_signed(rng, (level / _MAX_LEVEL) * 30.0),)
    if name == "Posterize":
        if increasing:
            return (4 - int((level / _MAX_LEVEL) * 4),)
        return (int((level / _MAX_LEVEL) * 4) + 4,)
    if name == "Solarize":
        if increasing:
            return (256 - int((level / _MAX_LEVEL) * 256),)
        return (int((level / _MAX_LEVEL) * 256),)
    if name == "SolarizeAdd":
        return (int((level / _MAX_LEVEL) * 110),)
    if name in ("Color", "Contrast", "Brightness", "Sharpness"):
        if increasing:
            return (_enhance_increasing(rng, level),)
        return (_enhance_plain(rng, level),)
    if name in ("ShearX", "ShearY"):
        return (_signed(rng, (level / _MAX_LEVEL) * 0.3),)
    if name in ("TranslateXRel", "TranslateYRel"):
        return (_signed(rng, (level / _MAX_LEVEL) * 0.45),)
    raise KeyError(name)


_OPS = {
    "AutoContrast": _auto_contrast,
    "Equalize": _equalize,
    "Invert": _invert,
    "Rotate": _rotate,
    "Posterize": _posterize,
    "Solarize": _solarize,
    "SolarizeAdd": _solarize_add,
    "Color": _color,
    "Contrast": _contrast,
    "Brightness": _brightness,
    "Sharpness": _sharpness,
    "ShearX": _shear_x,
    "ShearY": _shear_y,
    "TranslateXRel": _translate_x_rel,
    "TranslateYRel": _translate_y_rel,
}

_RAND_TRANSFORMS = [
    "AutoContrast",
    "Equalize",
    "Invert",
    "Rotate",
    "Posterize",
    "Solarize",
    "SolarizeAdd",
    "Color",
    "Contrast",
    "Brightness",
    "Sharpness",
    "ShearX",
    "ShearY",
    "TranslateXRel",
    "TranslateYRel",
]

_AUGMIX_TRANSFORMS = [
    "AutoContrast",
    "Equalize",
    "Rotate",
    "Posterize",
    "Solarize",
    "ShearX",
    "ShearY",
    "TranslateXRel",
    "TranslateYRel",
]


def _apply_op(img: Image.Image, name: str, rng, level: float, mstd: float, increasing: bool):
    if mstd > 0:
        level = level + rng.normal(0, mstd)
    level = float(np.clip(level, 0, _MAX_LEVEL))
    args = _level_args(name, rng, level, increasing)
    return _OPS[name](img, *args)


class RandAugment:
    """``rand-mN[-mstdS][-incB][-nL][-pP]``: L (default 2) ops drawn uniformly
    per image, each applied with probability P (default 0.5) at magnitude N
    (Gaussian-jittered by S)."""

    def __init__(self, magnitude=9.0, num_layers=2, mstd=0.5, increasing=False, prob=0.5):
        self.magnitude = magnitude
        self.num_layers = num_layers
        self.mstd = mstd
        self.increasing = increasing
        self.prob = prob

    def __call__(self, rng: np.random.Generator, img: np.ndarray) -> np.ndarray:
        pil = Image.fromarray(img)
        for _ in range(self.num_layers):
            name = _RAND_TRANSFORMS[int(rng.integers(len(_RAND_TRANSFORMS)))]
            if rng.random() <= self.prob:
                pil = _apply_op(pil, name, rng, self.magnitude, self.mstd, self.increasing)
        return np.asarray(pil)


class AugMix:
    """``augmix-mN[-wW][-dD][-aA]``: W (default 3) chains of depth D (default
    random 1–3), convexly mixed with Dirichlet(A) weights, then blended with
    the original via Beta(A, A)."""

    def __init__(self, magnitude=3.0, width=3, depth=-1, alpha=1.0, mstd=0.0):
        self.magnitude = magnitude
        self.width = width
        self.depth = depth
        self.alpha = alpha
        self.mstd = mstd

    def __call__(self, rng: np.random.Generator, img: np.ndarray) -> np.ndarray:
        ws = rng.dirichlet([self.alpha] * self.width).astype(np.float32)
        m = float(rng.beta(self.alpha, self.alpha))
        mix = np.zeros(img.shape, np.float32)
        for i in range(self.width):
            depth = self.depth if self.depth > 0 else int(rng.integers(1, 4))
            pil = Image.fromarray(img)
            for _ in range(depth):
                name = _AUGMIX_TRANSFORMS[int(rng.integers(len(_AUGMIX_TRANSFORMS)))]
                pil = _apply_op(pil, name, rng, self.magnitude, self.mstd, True)
            mix += ws[i] * np.asarray(pil, np.float32)
        out = (1 - m) * img.astype(np.float32) + m * mix
        return np.clip(out, 0, 255).astype(np.uint8)


# AutoAugment "original" ImageNet policy: (op, prob, magnitude-level) pairs.
_AUTO_POLICY = [
    [("Posterize", 0.4, 8), ("Rotate", 0.6, 9)],
    [("Solarize", 0.6, 5), ("AutoContrast", 0.6, 5)],
    [("Equalize", 0.8, 8), ("Equalize", 0.6, 3)],
    [("Posterize", 0.6, 7), ("Posterize", 0.6, 6)],
    [("Equalize", 0.4, 7), ("Solarize", 0.2, 4)],
    [("Equalize", 0.4, 4), ("Rotate", 0.8, 8)],
    [("Solarize", 0.6, 3), ("Equalize", 0.6, 7)],
    [("Posterize", 0.8, 5), ("Equalize", 1.0, 2)],
    [("Rotate", 0.2, 3), ("Solarize", 0.6, 8)],
    [("Equalize", 0.6, 8), ("Posterize", 0.4, 6)],
    [("Rotate", 0.8, 8), ("Color", 0.4, 0)],
    [("Rotate", 0.4, 9), ("Equalize", 0.6, 2)],
    [("Equalize", 0.0, 7), ("Equalize", 0.8, 8)],
    [("Invert", 0.6, 4), ("Equalize", 1.0, 8)],
    [("Color", 0.6, 4), ("Contrast", 1.0, 8)],
    [("Rotate", 0.8, 8), ("Color", 1.0, 2)],
    [("Color", 0.8, 8), ("Solarize", 0.8, 7)],
    [("Sharpness", 0.4, 7), ("Invert", 0.6, 8)],
    [("ShearX", 0.6, 5), ("Equalize", 1.0, 9)],
    [("Color", 0.4, 0), ("Equalize", 0.6, 3)],
    [("Equalize", 0.4, 7), ("Solarize", 0.2, 4)],
    [("Solarize", 0.6, 5), ("AutoContrast", 0.6, 5)],
    [("Invert", 0.6, 4), ("Equalize", 1.0, 8)],
    [("Color", 0.6, 4), ("Contrast", 1.0, 8)],
    [("Equalize", 0.8, 8), ("Equalize", 0.6, 3)],
]


class AutoAugment:
    """The original AutoAugment ImageNet policy (25 sub-policies of 2 ops)."""

    def __init__(self, mstd: float = 0.0):
        self.mstd = mstd

    def __call__(self, rng: np.random.Generator, img: np.ndarray) -> np.ndarray:
        pil = Image.fromarray(img)
        sub = _AUTO_POLICY[int(rng.integers(len(_AUTO_POLICY)))]
        for name, prob, level in sub:
            if rng.random() <= prob:
                pil = _apply_op(pil, name, rng, float(level), self.mstd, False)
        return np.asarray(pil)


def auto_augment_factory(policy: str):
    """Parse a timm-grammar policy string into a callable
    ``(rng, uint8 image) -> uint8 image`` — the counterpart of
    ``/root/reference/src/dataset.py:41-53``. Returns None for falsy input."""
    if not policy or policy == "none":
        return None
    parts = policy.split("-")
    kind = parts[0]
    kv: dict[str, float] = {}
    for tok in parts[1:]:
        m = re.fullmatch(r"([a-z]+)([\d.]+)", tok)
        if not m:
            raise ValueError(f"bad policy token {tok!r} in {policy!r}")
        kv[m.group(1)] = float(m.group(2))
    if kind == "rand":
        return RandAugment(
            magnitude=kv.get("m", 9.0),
            num_layers=int(kv.get("n", 2)),
            mstd=kv.get("mstd", 0.0),
            increasing=bool(int(kv.get("inc", 0))),
            prob=kv.get("p", 0.5),
        )
    if kind == "augmix":
        return AugMix(
            magnitude=kv.get("m", 3.0),
            width=int(kv.get("w", 3)),
            depth=int(kv.get("d", -1)),
            alpha=kv.get("a", 1.0),
            mstd=kv.get("mstd", 0.0),
        )
    return AutoAugment(mstd=kv.get("mstd", 0.0))
