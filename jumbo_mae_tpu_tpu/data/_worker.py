"""Data-worker subprocess entry point.

Workers are FRESH interpreters launched as ``python -m
jumbo_mae_tpu_tpu.data._worker`` — not ``multiprocessing`` children. That
sidesteps both classic loader failure modes at once: ``spawn`` re-imports the
user's ``__main__`` (breaks plain scripts and stdin sessions), and ``fork``
duplicates a parent that already holds multithreaded XLA/TPU runtime state
(deadlock risk the JAX runtime explicitly warns about). A fresh interpreter
imports only this module and never initializes an accelerator backend
(``JAX_PLATFORMS=cpu`` is exported by the parent as a belt-and-braces guard;
nothing here imports jax at all).

Protocol: the worker reads a JSON config blob from argv, then streams batches
to stdout as length-prefixed pickle frames:

    [8-byte big-endian length][pickle({"images": ..., "labels": ...})] ...

Backpressure is the pipe buffer: the parent reads frames into a bounded
queue; when it stops reading, the worker blocks on write. Worker death is an
EOF on the pipe — the parent detects it per worker instead of hanging.
"""

from __future__ import annotations

import json
import pickle
import struct
import sys


def _run(cfg_json: str) -> None:
    from jumbo_mae_tpu_tpu.data.loader import (
        DataConfig,
        StreamCursor,
        batch_train_samples,
        train_sample_stream,
    )
    from jumbo_mae_tpu_tpu.data.resize import ShardLedger

    spec = json.loads(cfg_json)
    cfg = DataConfig(**spec["data"])
    start_epoch = spec.get("start_epoch", 0)
    cursor = StreamCursor(start_epoch, spec.get("skip_samples", 0))
    ledger = ShardLedger(preconsumed=spec.get("shard_preconsumed"))
    override = spec.get("epoch_shard_override")
    stream = train_sample_stream(
        cfg,
        process_index=spec["process_index"],
        process_count=spec["process_count"],
        worker_index=spec["worker_index"],
        worker_count=spec["worker_count"],
        start_epoch=start_epoch,
        skip_samples=spec.get("skip_samples", 0),
        cursor=cursor,
        ledger=ledger,
        epoch_shard_override=override,
    )
    out = sys.stdout.buffer
    for batch in batch_train_samples(
        stream, spec["batch_size"], cfg.repeats, cursor=cursor, ledger=ledger
    ):
        payload = pickle.dumps(batch, protocol=pickle.HIGHEST_PROTOCOL)
        out.write(struct.pack(">Q", len(payload)))
        out.write(payload)
        out.flush()


def main() -> None:
    if len(sys.argv) != 2:
        raise SystemExit("usage: python -m jumbo_mae_tpu_tpu.data._worker <json>")
    try:
        _run(sys.argv[1])
    except (BrokenPipeError, KeyboardInterrupt):
        pass


if __name__ == "__main__":
    main()
