"""Host-side image transforms (numpy/cv2), torchvision/timm-equivalent.

The reference composed torchvision-v2 + timm transforms on PIL images
(``/root/reference/src/dataset.py:56-82``): RandomResizedCrop(scale 0.2–1.0,
bicubic) or "SRC" (Resize + RandomCrop pad-4 reflect), HFlip, auto-augment,
optional ColorJitter, optional RandomErasing(value="random"), and for eval
Resize(size/crop_ratio) + CenterCrop. These are fresh numpy/cv2
implementations of the same distributions — every function takes an explicit
``np.random.Generator`` so a worker's sample stream is reproducible from a
single seed (the reference inherited torch's opaque per-worker RNG).

Images are (H, W, C) uint8 RGB throughout; outputs stay uint8 — the uint8 →
float normalization happens ON DEVICE (``ops/preprocess.py``), preserving the
reference's small-host-transfer trick (``/root/reference/src/pretraining.py:88-91``).
"""

from __future__ import annotations

import math

import numpy as np

try:  # pragma: no cover
    import cv2

    cv2.setNumThreads(0)
except ImportError:  # pragma: no cover
    cv2 = None

_CV2_INTERP = {}
if cv2 is not None:
    _CV2_INTERP = {
        "bilinear": cv2.INTER_LINEAR,
        "bicubic": cv2.INTER_CUBIC,
        "nearest": cv2.INTER_NEAREST,
        "area": cv2.INTER_AREA,
    }


def resize(img: np.ndarray, size: tuple[int, int], interpolation: str = "bicubic") -> np.ndarray:
    """Resize to (height, width)."""
    h, w = size
    if img.shape[:2] == (h, w):
        return img
    if cv2 is not None:
        return cv2.resize(img, (w, h), interpolation=_CV2_INTERP[interpolation])
    from PIL import Image

    pil = Image.fromarray(img).resize(
        (w, h), {"bicubic": Image.BICUBIC, "bilinear": Image.BILINEAR, "nearest": Image.NEAREST, "area": Image.BOX}[interpolation]
    )
    return np.asarray(pil)


def resize_shorter(img: np.ndarray, shorter: int, interpolation: str = "bicubic") -> np.ndarray:
    h, w = img.shape[:2]
    if h <= w:
        return resize(img, (shorter, max(1, round(w * shorter / h))), interpolation)
    return resize(img, (max(1, round(h * shorter / w)), shorter), interpolation)


def center_crop(img: np.ndarray, size: int) -> np.ndarray:
    h, w = img.shape[:2]
    if h < size or w < size:  # pad-to-fit like torchvision CenterCrop
        pt, pl = max(0, (size - h) // 2), max(0, (size - w) // 2)
        img = np.pad(
            img,
            ((pt, max(0, size - h - pt)), (pl, max(0, size - w - pl)), (0, 0)),
        )
        h, w = img.shape[:2]
    top, left = (h - size) // 2, (w - size) // 2
    return img[top : top + size, left : left + size]


def eval_transform(
    img: np.ndarray, size: int, *, crop_ratio: float = 0.875, interpolation: str = "bicubic"
) -> np.ndarray:
    """Resize(size / crop_ratio) shorter side + CenterCrop(size) — the eval
    pipeline at ``/root/reference/src/dataset.py:76-82``."""
    img = resize_shorter(img, int(round(size / crop_ratio)), interpolation)
    return center_crop(img, size)


def random_resized_crop(
    rng: np.random.Generator,
    img: np.ndarray,
    size: int,
    *,
    scale: tuple[float, float] = (0.2, 1.0),
    ratio: tuple[float, float] = (3 / 4, 4 / 3),
    interpolation: str = "bicubic",
) -> np.ndarray:
    """torchvision RandomResizedCrop distribution: 10 rejection-sampling
    attempts over (area, log-uniform aspect), then central fallback."""
    h, w = img.shape[:2]
    area = h * w
    log_ratio = (math.log(ratio[0]), math.log(ratio[1]))
    for _ in range(10):
        target_area = area * rng.uniform(scale[0], scale[1])
        aspect = math.exp(rng.uniform(*log_ratio))
        cw = int(round(math.sqrt(target_area * aspect)))
        ch = int(round(math.sqrt(target_area / aspect)))
        if 0 < cw <= w and 0 < ch <= h:
            top = int(rng.integers(0, h - ch + 1))
            left = int(rng.integers(0, w - cw + 1))
            crop = img[top : top + ch, left : left + cw]
            return resize(crop, (size, size), interpolation)
    # fallback: center crop at the in-range aspect closest to the image's
    in_ratio = w / h
    if in_ratio < ratio[0]:
        cw, ch = w, int(round(w / ratio[0]))
    elif in_ratio > ratio[1]:
        ch, cw = h, int(round(h * ratio[1]))
    else:
        cw, ch = w, h
    top, left = (h - ch) // 2, (w - cw) // 2
    return resize(img[top : top + ch, left : left + cw], (size, size), interpolation)


def simple_resize_crop(
    rng: np.random.Generator, img: np.ndarray, size: int, *, interpolation: str = "bicubic"
) -> np.ndarray:
    """The reference's "src" mode: Resize(size) + RandomCrop(size, padding=4,
    reflect) (``/root/reference/src/dataset.py:62-67``)."""
    img = resize_shorter(img, size, interpolation)
    img = np.pad(img, ((4, 4), (4, 4), (0, 0)), mode="reflect")
    h, w = img.shape[:2]
    top = int(rng.integers(0, h - size + 1))
    left = int(rng.integers(0, w - size + 1))
    return img[top : top + size, left : left + size]


def random_hflip(rng: np.random.Generator, img: np.ndarray, p: float = 0.5) -> np.ndarray:
    if rng.random() < p:
        return img[:, ::-1]
    return img


def _blend(a: np.ndarray, b: np.ndarray, factor: float) -> np.ndarray:
    out = b.astype(np.float32) + factor * (a.astype(np.float32) - b.astype(np.float32))
    return np.clip(out, 0, 255).astype(np.uint8)


def adjust_brightness(img: np.ndarray, factor: float) -> np.ndarray:
    return _blend(img, np.zeros_like(img), factor)


def adjust_contrast(img: np.ndarray, factor: float) -> np.ndarray:
    # PIL semantics: blend toward the mean of the grayscale image
    gray = (img @ np.array([0.299, 0.587, 0.114], np.float32)).mean()
    return _blend(img, np.full_like(img, int(gray + 0.5)), factor)


def adjust_saturation(img: np.ndarray, factor: float) -> np.ndarray:
    gray = (img @ np.array([0.299, 0.587, 0.114], np.float32)).astype(np.uint8)
    return _blend(img, gray[..., None].repeat(3, axis=-1), factor)


def adjust_hue(img: np.ndarray, delta: float) -> np.ndarray:
    """delta in [-0.5, 0.5] turns of the hue wheel."""
    if cv2 is None or abs(delta) < 1e-8:
        return img
    hsv = cv2.cvtColor(img, cv2.COLOR_RGB2HSV)
    hsv[..., 0] = (hsv[..., 0].astype(np.int32) + int(round(delta * 180))) % 180
    return cv2.cvtColor(hsv, cv2.COLOR_HSV2RGB)


def color_jitter(
    rng: np.random.Generator,
    img: np.ndarray,
    strength: float,
    *,
    hue: float = 0.0,
) -> np.ndarray:
    """torchvision ColorJitter(strength, strength, strength[, hue]): each of
    brightness/contrast/saturation drawn from U[max(0,1-s), 1+s], applied in
    a random order."""
    ops = []
    for fn in (adjust_brightness, adjust_contrast, adjust_saturation):
        factor = rng.uniform(max(0.0, 1 - strength), 1 + strength)
        ops.append((fn, factor))
    if hue > 0:
        ops.append((adjust_hue, rng.uniform(-hue, hue)))
    for i in rng.permutation(len(ops)):
        fn, factor = ops[i]
        img = fn(img, factor)
    return img


def random_erasing(
    rng: np.random.Generator,
    img: np.ndarray,
    p: float,
    *,
    scale: tuple[float, float] = (0.02, 1 / 3),
    ratio: tuple[float, float] = (0.3, 3.3),
    attempts: int = 10,
) -> np.ndarray:
    """torchvision RandomErasing(value="random"): erase a random rect with
    uniform noise. Mutates a copy; returns the input untouched with prob 1-p."""
    if rng.random() >= p:
        return img
    h, w = img.shape[:2]
    area = h * w
    log_ratio = (math.log(ratio[0]), math.log(ratio[1]))
    for _ in range(attempts):
        target = area * rng.uniform(*scale)
        aspect = math.exp(rng.uniform(*log_ratio))
        eh = int(round(math.sqrt(target * aspect)))
        ew = int(round(math.sqrt(target / aspect)))
        if 0 < eh < h and 0 < ew < w:
            top = int(rng.integers(0, h - eh + 1))
            left = int(rng.integers(0, w - ew + 1))
            out = img.copy()
            out[top : top + eh, left : left + ew] = rng.integers(
                0, 256, (eh, ew, img.shape[2]), dtype=np.uint8
            )
            return out
    return img
