"""ctypes binding for the native tar reader (``native/tario.cc``).

Builds ``libtario.so`` on first use with g++ (cached beside the source);
everything degrades gracefully — ``available()`` is False when no toolchain
exists and callers fall back to the pure-Python ``tario`` path.

Usage:
    with NativeShardReader(urls, threads=8) as r:
        for image_bytes, label in r: ...
"""

from __future__ import annotations

import ctypes
import subprocess
import threading
from pathlib import Path

_NATIVE_DIR = Path(__file__).resolve().parent.parent.parent / "native"
_SO_PATH = _NATIVE_DIR / "libtario.so"
_build_lock = threading.Lock()
_lib = None


def _build() -> bool:
    src = _NATIVE_DIR / "tario.cc"
    if not src.exists():
        return False
    if _SO_PATH.exists() and _SO_PATH.stat().st_mtime >= src.stat().st_mtime:
        return True
    try:
        subprocess.run(
            [
                "g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                "-o", str(_SO_PATH), str(src), "-lpthread",
            ],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def _load():
    global _lib
    if _lib is not None:
        return _lib
    with _build_lock:
        if _lib is not None:
            return _lib
        if not _build():
            return None
        lib = ctypes.CDLL(str(_SO_PATH))
        lib.tario_open.restype = ctypes.c_void_p
        lib.tario_open.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ]
        lib.tario_next.restype = ctypes.c_int
        lib.tario_next.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_void_p),
        ]
        lib.tario_free.argtypes = [ctypes.c_void_p]
        lib.tario_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


class NativeShardReader:
    """Iterate (image_bytes, label) pairs produced by native reader threads.

    ``loop=True`` re-reads the shard list forever (training);
    ``loop=False`` is one pass (eval). Not async-safe across iterators —
    one consumer per reader.
    """

    def __init__(
        self,
        urls: list[str],
        *,
        threads: int = 4,
        queue_capacity: int = 256,
        loop: bool = False,
    ):
        lib = _load()
        if lib is None:
            raise RuntimeError("native tario library unavailable (no g++?)")
        if not urls:
            raise ValueError("no shard urls")
        self._lib = lib
        blob = b"".join(u.encode() + b"\0" for u in urls) + b"\0"
        self._handle = lib.tario_open(
            blob, int(threads), int(queue_capacity), int(loop)
        )
        self._closed = False

    def __iter__(self):
        return self

    def __next__(self) -> tuple[bytes, int]:
        if self._closed:
            raise StopIteration
        data = ctypes.POINTER(ctypes.c_uint8)()
        length = ctypes.c_int64()
        label = ctypes.c_int64()
        token = ctypes.c_void_p()
        ok = self._lib.tario_next(
            self._handle,
            ctypes.byref(data),
            ctypes.byref(length),
            ctypes.byref(label),
            ctypes.byref(token),
        )
        if not ok:
            self.close()
            raise StopIteration
        try:
            payload = ctypes.string_at(data, length.value)
        finally:
            self._lib.tario_free(token)
        return payload, int(label.value)

    def close(self):
        if not self._closed:
            self._closed = True
            self._lib.tario_close(self._handle)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass
