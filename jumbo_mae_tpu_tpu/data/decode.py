"""Image decoding: JPEG/PNG bytes → RGB uint8 HWC numpy.

The reference leaned on Pillow-SIMD + libjpeg-turbo installed at setup time
(``/root/reference/scripts/setup.sh:31-34``) and webdataset's
``decode("pil")``. Here the default decoder is OpenCV (ships its own
libjpeg-turbo, SIMD-enabled) with a PIL fallback for formats cv2 rejects.
Corrupt images return ``None`` so the pipeline can skip them — the
``ignore_and_continue`` contract.
"""

from __future__ import annotations

import io
import logging

import numpy as np

logger = logging.getLogger(__name__)

try:  # pragma: no cover - import guard
    import cv2

    cv2.setNumThreads(0)  # decode parallelism belongs to the worker pool
except ImportError:  # pragma: no cover
    cv2 = None

IMAGE_EXTS = ("jpg", "jpeg", "png", "ppm", "bmp", "webp")


def decode_image(payload: bytes) -> np.ndarray | None:
    """Decode image bytes to (H, W, 3) RGB uint8, or None if undecodable."""
    if cv2 is not None:
        buf = np.frombuffer(payload, np.uint8)
        bgr = cv2.imdecode(buf, cv2.IMREAD_COLOR)
        if bgr is not None:
            return np.ascontiguousarray(bgr[..., ::-1])
    try:
        from PIL import Image

        with Image.open(io.BytesIO(payload)) as im:
            return np.asarray(im.convert("RGB"))
    except Exception as e:  # noqa: BLE001 - any decode failure → skip sample
        logger.warning("undecodable image (%d bytes): %s", len(payload), e)
        return None


def decode_label(payload: bytes | str) -> int:
    """Decode a ``.cls`` member (ASCII integer) to int."""
    if isinstance(payload, bytes):
        payload = payload.decode("utf-8")
    return int(payload.strip())


def find_image_key(sample: dict) -> str | None:
    for ext in IMAGE_EXTS:
        if ext in sample:
            return ext
    return None
