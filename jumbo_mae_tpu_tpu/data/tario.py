"""Streaming tar reading: URL → byte stream → grouped samples.

Replaces the reference's ``wds.gopen`` + ``tarfile_to_samples`` pair
(``/root/reference/src/dataset.py:113-119``, ``/root/reference/src/utils.py:55-63``
for the write side). A sample is all consecutive tar members sharing a
basename stem: ``n01440764_10026.jpg`` + ``n01440764_10026.cls`` →
``{"__key__": "n01440764_10026", "jpg": b..., "cls": b...}``.

Supported URL schemes (both read and write):

- plain local paths / ``file://``;
- ``pipe:CMD`` — run CMD in a shell, read its stdout (write: its stdin); this
  is the escape hatch that makes every remote store work (``pipe:gsutil cat
  gs://...``), exactly the contract webdataset exposed;
- ``gs://`` — sugar for the gsutil pipe;
- ``http(s)://`` — urllib streaming read.

Corrupt tar members or truncated archives are skipped with a warning, the
reference's ``ignore_and_continue`` policy — but no longer *silently*: both
are counted in the obs registry (``data_corrupt_members_total``,
``data_truncated_shards_total``), and shard-level read failures now get
**retries with capped exponential backoff** (transient GCS/pipe blips heal
in place, resuming exactly past the samples already yielded) before the
shard is **quarantined** for the rest of the pass — logged, counted
(``data_shards_quarantined_total``), and surfaced through ``/healthz`` —
instead of being dropped on the first error.
"""

from __future__ import annotations

import io
import logging
import random
import subprocess
import tarfile
import threading
import time
from collections.abc import Iterator
from contextlib import contextmanager
from dataclasses import dataclass
from urllib.parse import urlparse

from jumbo_mae_tpu_tpu.faults.inject import fault_point
from jumbo_mae_tpu_tpu.obs.metrics import get_registry

logger = logging.getLogger(__name__)

Sample = dict[str, bytes | str]


class TruncatedShardError(OSError):
    """A tar stream ended mid-archive. OSError subclass on purpose: the
    retry loop treats truncation as transient (a cut network read and a
    truncated file at rest are indistinguishable from here)."""


@dataclass(frozen=True)
class RetryPolicy:
    """Shard-read retry knobs (``data.shard_retries`` /
    ``data.shard_retry_backoff_s`` in recipes)."""

    attempts: int = 3        # total tries per shard per pass
    backoff_s: float = 0.05  # first sleep; doubles per retry
    max_backoff_s: float = 5.0
    jitter: float = 0.25     # +- fraction of the sleep, decorrelates workers

    def sleep_s(self, retry_index: int, rng: random.Random) -> float:
        base = min(self.backoff_s * (2.0 ** retry_index), self.max_backoff_s)
        return base * (1.0 + self.jitter * (2.0 * rng.random() - 1.0))


class ShardQuarantine:
    """Process-global record of shards given up on (after retries).

    The *skip* decision is per-pass — each epoch retries a previously bad
    shard, so a healed store heals the stream — but the record accumulates
    for observability: ``snapshot()`` feeds the ``/healthz`` probe wired by
    ``cli/train.py``. Worker subprocesses keep their own instance (their
    registries are per-process too); the inline and native-IO paths feed
    the exporter directly.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._items: dict[str, str] = {}

    def add(self, url: str, reason: str) -> None:
        with self._lock:
            self._items[url] = reason
        get_registry().counter(
            "data_shards_quarantined_total",
            "shards abandoned after exhausting read retries",
        ).inc()

    def snapshot(self) -> dict[str, str]:
        with self._lock:
            return dict(self._items)

    def clear(self) -> None:
        with self._lock:
            self._items.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)


QUARANTINE = ShardQuarantine()


@contextmanager
def open_url(url: str, mode: str = "rb"):
    """Open a shard URL as a (possibly piped) binary stream."""
    if mode not in ("rb", "wb"):
        raise ValueError(f"mode must be rb/wb, got {mode!r}")
    write = mode == "wb"
    if url.startswith("pipe:"):
        cmd = url[len("pipe:") :]
        proc = subprocess.Popen(
            cmd,
            shell=True,
            stdin=subprocess.PIPE if write else None,
            stdout=None if write else subprocess.PIPE,
        )
        stream = proc.stdin if write else proc.stdout
        try:
            yield stream
        finally:
            stream.close()
            ret = proc.wait()
            if ret != 0:
                raise RuntimeError(f"pipe command failed ({ret}): {cmd}")
        return
    if url.startswith("gs://"):
        q = shell_quote(url)
        pipe = f"pipe:gsutil cp - {q}" if write else f"pipe:gsutil cat {q}"
        with open_url(pipe, mode) as s:
            yield s
        return
    if url.startswith(("http://", "https://")):
        if write:
            raise ValueError("cannot write to http(s) URLs")
        import urllib.request

        with urllib.request.urlopen(url) as s:
            yield s
        return
    path = urlparse(url).path if url.startswith("file://") else url
    with open(path, mode) as s:
        yield s


def shell_quote(s: str) -> str:
    import shlex

    return shlex.quote(s)


def iter_tar(stream, *, strict: bool = False) -> Iterator[tuple[str, bytes]]:
    """Yield (member_name, payload) from a non-seekable tar stream.

    Corrupt members are skipped and counted; a truncated archive stops the
    shard and is counted — and with ``strict=True`` additionally raises
    :class:`TruncatedShardError` so the retry layer can re-read the shard
    (a truncated *network read* heals on retry; a truncated file at rest
    exhausts the attempts and quarantines, same net data as before but
    visible on ``/metrics`` instead of a log line nobody reads).
    """
    reg = get_registry()
    try:
        with tarfile.open(fileobj=stream, mode="r|*") as tar:
            for member in tar:
                if not member.isreg():
                    continue
                f = tar.extractfile(member)
                if f is None:
                    continue
                try:
                    yield member.name, f.read()
                except tarfile.TarError as e:  # corrupt member: skip
                    reg.counter(
                        "data_corrupt_members_total",
                        "corrupt tar members skipped",
                    ).inc()
                    logger.warning("skipping corrupt member %s: %s", member.name, e)
    except tarfile.TarError as e:  # truncated archive: stop this shard
        reg.counter(
            "data_truncated_shards_total",
            "tar streams that ended mid-archive",
        ).inc()
        logger.warning("truncated/corrupt tar stream: %s", e)
        if strict:
            raise TruncatedShardError(str(e)) from e


def _split_member(name: str) -> tuple[str, str]:
    """``dir/key.ext`` → (``dir/key``, ``ext``); extension is everything after
    the FIRST dot of the basename (webdataset convention, so ``x.seg.png``
    keys on ``seg.png``)."""
    slash = name.rfind("/")
    dot = name.find(".", slash + 1)
    if dot < 0:
        return name, ""
    return name[:dot], name[dot + 1 :].lower()


def group_samples(members: Iterator[tuple[str, bytes]]) -> Iterator[Sample]:
    """Group consecutive members with a shared stem into sample dicts."""
    current: Sample = {}
    key: str | None = None
    for name, payload in members:
        stem, ext = _split_member(name)
        if stem != key:
            if current:
                yield current
            current, key = {"__key__": stem}, stem
        current[ext] = payload
    if current:
        yield current


def iter_tar_samples(
    url: str, retry: RetryPolicy | None = None
) -> Iterator[Sample]:
    """Stream one shard URL as grouped samples; never raises on bad data.

    Transient read failures (``OSError`` — including truncation under
    ``strict`` tar reading — and pipe ``RuntimeError``) are retried with
    capped, jittered exponential backoff. A retry **re-reads the shard and
    resumes exactly past the samples already yielded** (tar order is
    deterministic), so a shard that fails twice then succeeds contributes
    the identical sample sequence as a fault-free read. When every attempt
    fails the shard is recorded in :data:`QUARANTINE` and the stream moves
    on — the epoch survives, the loss is visible on ``/metrics``.
    """
    policy = retry or RetryPolicy()
    rng = random.Random(url)  # str seeds hash-randomization-free (sha512)
    yielded = 0
    closing = False
    last_err: BaseException | None = None
    for attempt in range(max(1, policy.attempts)):
        try:
            fault_point("data.shard_open", key=url)
            with open_url(url) as stream:
                for i, sample in enumerate(
                    group_samples(iter_tar(stream, strict=True))
                ):
                    if i < yielded:  # replay of an already-consumed prefix
                        continue
                    yielded += 1
                    try:
                        yield sample
                    except GeneratorExit:
                        # consumer closed us mid-shard — pipe teardown may
                        # surface as RuntimeError below; not a read failure
                        closing = True
                        raise
            return
        except (OSError, RuntimeError) as e:
            if closing:
                return
            last_err = e
            if attempt + 1 >= max(1, policy.attempts):
                break
            get_registry().counter(
                "data_shard_retries_total",
                "shard reads retried after a transient failure",
            ).inc()
            delay = policy.sleep_s(attempt, rng)
            logger.warning(
                "shard %s read failed (attempt %d/%d): %s — retrying in %.2fs",
                url, attempt + 1, policy.attempts, e, delay,
            )
            time.sleep(delay)
    logger.error(
        "quarantining shard %s after %d attempts: %s",
        url, policy.attempts, last_err,
    )
    QUARANTINE.add(url, f"{type(last_err).__name__}: {last_err}")


def iter_shards_samples(
    urls: list[str], retry: RetryPolicy | None = None
) -> Iterator[Sample]:
    """Stream several shards back to back, tagging each sample with its
    ``__url__`` (useful for resume diagnostics). A shard that exhausts its
    read retries is skipped (quarantined for this pass); the remaining
    shards still stream — one bad shard never kills the epoch."""
    for url in urls:
        for sample in iter_tar_samples(url, retry=retry):
            sample["__url__"] = url
            yield sample


def write_tar_samples(url: str, samples: list[Sample]) -> None:
    """Write samples to a tar shard (test fixtures; dataset prep tooling)."""
    with open_url(url, "wb") as stream:
        with tarfile.open(fileobj=stream, mode="w|") as tar:
            for sample in samples:
                key = str(sample["__key__"])
                for ext, payload in sample.items():
                    if ext.startswith("__"):
                        continue
                    assert isinstance(payload, bytes), (key, ext)
                    info = tarfile.TarInfo(f"{key}.{ext}")
                    info.size = len(payload)
                    tar.addfile(info, io.BytesIO(payload))
