"""Streaming tar reading: URL → byte stream → grouped samples.

Replaces the reference's ``wds.gopen`` + ``tarfile_to_samples`` pair
(``/root/reference/src/dataset.py:113-119``, ``/root/reference/src/utils.py:55-63``
for the write side). A sample is all consecutive tar members sharing a
basename stem: ``n01440764_10026.jpg`` + ``n01440764_10026.cls`` →
``{"__key__": "n01440764_10026", "jpg": b..., "cls": b...}``.

Supported URL schemes (both read and write):

- plain local paths / ``file://``;
- ``pipe:CMD`` — run CMD in a shell, read its stdout (write: its stdin); this
  is the escape hatch that makes every remote store work (``pipe:gsutil cat
  gs://...``), exactly the contract webdataset exposed;
- ``gs://`` — sugar for the gsutil pipe;
- ``http(s)://`` — urllib streaming read.

Corrupt tar members or truncated archives are skipped with a warning, the
reference's ``ignore_and_continue`` policy.
"""

from __future__ import annotations

import io
import logging
import subprocess
import tarfile
from collections.abc import Iterator
from contextlib import contextmanager
from urllib.parse import urlparse

logger = logging.getLogger(__name__)

Sample = dict[str, bytes | str]


@contextmanager
def open_url(url: str, mode: str = "rb"):
    """Open a shard URL as a (possibly piped) binary stream."""
    if mode not in ("rb", "wb"):
        raise ValueError(f"mode must be rb/wb, got {mode!r}")
    write = mode == "wb"
    if url.startswith("pipe:"):
        cmd = url[len("pipe:") :]
        proc = subprocess.Popen(
            cmd,
            shell=True,
            stdin=subprocess.PIPE if write else None,
            stdout=None if write else subprocess.PIPE,
        )
        stream = proc.stdin if write else proc.stdout
        try:
            yield stream
        finally:
            stream.close()
            ret = proc.wait()
            if ret != 0:
                raise RuntimeError(f"pipe command failed ({ret}): {cmd}")
        return
    if url.startswith("gs://"):
        q = shell_quote(url)
        pipe = f"pipe:gsutil cp - {q}" if write else f"pipe:gsutil cat {q}"
        with open_url(pipe, mode) as s:
            yield s
        return
    if url.startswith(("http://", "https://")):
        if write:
            raise ValueError("cannot write to http(s) URLs")
        import urllib.request

        with urllib.request.urlopen(url) as s:
            yield s
        return
    path = urlparse(url).path if url.startswith("file://") else url
    with open(path, mode) as s:
        yield s


def shell_quote(s: str) -> str:
    import shlex

    return shlex.quote(s)


def iter_tar(stream) -> Iterator[tuple[str, bytes]]:
    """Yield (member_name, payload) from a non-seekable tar stream."""
    try:
        with tarfile.open(fileobj=stream, mode="r|*") as tar:
            for member in tar:
                if not member.isreg():
                    continue
                f = tar.extractfile(member)
                if f is None:
                    continue
                try:
                    yield member.name, f.read()
                except tarfile.TarError as e:  # corrupt member: skip
                    logger.warning("skipping corrupt member %s: %s", member.name, e)
    except tarfile.TarError as e:  # truncated archive: stop this shard
        logger.warning("truncated/corrupt tar stream: %s", e)


def _split_member(name: str) -> tuple[str, str]:
    """``dir/key.ext`` → (``dir/key``, ``ext``); extension is everything after
    the FIRST dot of the basename (webdataset convention, so ``x.seg.png``
    keys on ``seg.png``)."""
    slash = name.rfind("/")
    dot = name.find(".", slash + 1)
    if dot < 0:
        return name, ""
    return name[:dot], name[dot + 1 :].lower()


def group_samples(members: Iterator[tuple[str, bytes]]) -> Iterator[Sample]:
    """Group consecutive members with a shared stem into sample dicts."""
    current: Sample = {}
    key: str | None = None
    for name, payload in members:
        stem, ext = _split_member(name)
        if stem != key:
            if current:
                yield current
            current, key = {"__key__": stem}, stem
        current[ext] = payload
    if current:
        yield current


def iter_tar_samples(url: str) -> Iterator[Sample]:
    """Stream one shard URL as grouped samples; never raises on bad data."""
    try:
        with open_url(url) as stream:
            yield from group_samples(iter_tar(stream))
    except (OSError, RuntimeError) as e:
        logger.warning("skipping unreadable shard %s: %s", url, e)


def iter_shards_samples(urls: list[str]) -> Iterator[Sample]:
    """Stream several shards back to back, tagging each sample with its
    ``__url__`` (useful for resume diagnostics)."""
    for url in urls:
        for sample in iter_tar_samples(url):
            sample["__url__"] = url
            yield sample


def write_tar_samples(url: str, samples: list[Sample]) -> None:
    """Write samples to a tar shard (test fixtures; dataset prep tooling)."""
    with open_url(url, "wb") as stream:
        with tarfile.open(fileobj=stream, mode="w|") as tar:
            for sample in samples:
                key = str(sample["__key__"])
                for ext, payload in sample.items():
                    if ext.startswith("__"):
                        continue
                    assert isinstance(payload, bytes), (key, ext)
                    info = tarfile.TarInfo(f"{key}.{ext}")
                    info.size = len(payload)
                    tar.addfile(info, io.BytesIO(payload))
