"""Round benchmark: MAE ViT-L/16 pretrain throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference published no throughput numbers (BASELINE.md), so the baseline
here is a faithful *reference-style* configuration of the same workload run
on the same chip: float32 compute (the reference's flax modules never cast
to bfloat16) with the same model/masking/optimizer. ``vs_baseline`` is
(this framework's bf16 throughput) / (reference-style fp32 throughput).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np

# Filled in as the bench progresses so the watchdog / error path can emit
# whatever was measured before things went sideways.
_partial: dict = {}


def _emit_error(message: str) -> None:
    """Print the machine-readable failure line (same stdout contract as the
    success path, plus an ``error`` field) so the round artifact records WHY
    even when the backend is down."""
    line = {
        "metric": _partial.get(
            "metric", "mae_vit_pretrain_imgs_per_sec_per_chip"
        ),
        "value": _partial.get("value"),
        "unit": "imgs/sec/chip",
        "vs_baseline": _partial.get("vs_baseline"),
        "error": message[-600:],
    }
    print(json.dumps(line), flush=True)


def _start_watchdog(budget_s: float) -> None:
    """Hard wall-clock bound: a wedged remote-TPU tunnel can make any device
    op block forever (observed round 2 — rc 124, no output). When the budget
    expires, print the JSON error line with partial results and exit hard;
    an artifact that says "hung after the bf16 leg" beats a bare timeout."""

    def fire():
        _emit_error(
            f"bench watchdog fired after {budget_s:.0f}s "
            f"(completed: {sorted(_partial) or 'nothing'})"
        )
        os._exit(1)

    t = threading.Timer(budget_s, fire)
    t.daemon = True
    t.start()


def _probe_backend_once(timeout_s: float) -> tuple[bool, str]:
    """Run a trivial jitted op in a short-fused subprocess with THIS process's
    env (same backend the bench will get). Returns (ok, detail). A subprocess
    is the only hang-proof probe: on a wedged tunnel, backend init *blocks*
    rather than raising, and nothing in-process can recover from that."""
    forced = os.environ.get("BENCH_FORCE_PROBE_FAIL")
    if forced:  # test hook for the JSON-error paths
        if forced == "transient":
            return False, "UNAVAILABLE (forced by BENCH_FORCE_PROBE_FAIL)"
        return False, "forced permanent probe failure"
    try:
        proc = subprocess.run(
            [
                sys.executable,
                "-c",
                "import jax, jax.numpy as jnp; "
                "print(float(jax.jit(lambda x: x.sum())(jnp.ones(8))))",
            ],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return False, f"backend probe hung (> {timeout_s:.0f}s)"
    if proc.returncode != 0:
        return False, f"backend probe failed: {proc.stderr[-400:]}"
    return True, ""


_TRANSIENT = ("UNAVAILABLE", "unavailable", "DEADLINE_EXCEEDED", "hung")


def acquire_backend(
    *, deadline_s: float | None = None, probe_timeout_s: float | None = None
) -> None:
    """Block until the accelerator backend answers a trivial op, retrying
    transient failures (UNAVAILABLE / hang) until ``deadline_s``. Permanent
    failures (misconfigured platform, import error) raise immediately.
    Only after this returns does the bench initialize jax in-process."""
    deadline_s = float(
        os.environ.get("BENCH_ACQUIRE_DEADLINE", deadline_s or 240)
    )
    probe_timeout_s = float(
        os.environ.get("BENCH_PROBE_TIMEOUT", probe_timeout_s or 60)
    )
    start = time.monotonic()
    attempt = 0
    while True:
        attempt += 1
        ok, detail = _probe_backend_once(probe_timeout_s)
        if ok:
            return
        if not any(tag in detail for tag in _TRANSIENT):
            raise RuntimeError(f"backend permanently unusable: {detail}")
        elapsed = time.monotonic() - start
        if elapsed + 15 >= deadline_s:
            raise RuntimeError(
                f"backend still unavailable after {attempt} probes / "
                f"{elapsed:.0f}s: {detail}"
            )
        print(
            f"bench: backend unavailable (attempt {attempt}: {detail.splitlines()[0][:120]}); "
            f"retrying, {deadline_s - elapsed:.0f}s left",
            file=sys.stderr,
            flush=True,
        )
        time.sleep(min(15, max(0.0, deadline_s - elapsed)))


MODELS = {
    # test-sized smoke config: fast bench/profile sanity on any backend
    "vit_t16": dict(dec=dict(layers=2, dim=64, heads=4), batch=8, remat=False),
    # the reference's OTHER headline pretrain workload (B/16 1600ep,
    # /root/reference/config/pretrain/pretrain-vit-b16-224-in1k-1600ep.sh);
    # same 8x512x16h decoder as L
    "vit_b16": dict(
        dec=dict(layers=8, dim=512, heads=16),
        # swept on-chip: 192 peaks (1285 vs 1210@128, 1236@256, 1184@384,
        # 1115@512); onehot gather loses ~3% at every batch (like L)
        batch=192,
        f32_batch=128,
        remat=False,
        bf16=dict(mu_dtype="bfloat16", nu_dtype="bfloat16"),
    ),
    "vit_l16": dict(
        dec=dict(layers=8, dim=512, heads=16),
        # 192 re-swept fastest once bf16 moments landed (669.6 vs 654.0@128,
        # 653.7@160, 617.3@224 — the pre-bf16 sweeps had 128 winning); the
        # f32 reference leg stays at its established 128.
        batch=192,
        f32_batch=128,
        remat=False,
        # bf16-leg defaults (PERF.md §Round 3 on-chip, vit_l16 sweep):
        # bf16 moments +1.3%; onehot gather is a clear LOSS here (−8%,
        # the opposite of vit_h14 — the 0/1 matmuls outgrow the gather
        # saving at batch 128 / decoder dim 512), so take stays.
        bf16=dict(mu_dtype="bfloat16", nu_dtype="bfloat16"),
    ),
    # The reference-style f32 leg doubles every activation, so it gets its
    # own largest-fitting batch (f32 at the bf16 leg's batch needs ~20 GB);
    # the ratio compares per-image throughput, each leg at its feasible
    # batch, plus an equal-batch ratio in the JSON. The f32 leg keeps the
    # dots remat that batch 32 f32 needs to fit on 16 GB.
    "vit_h14": dict(
        dec=dict(layers=8, dim=512, heads=16),
        # batch 72 re-swept fastest once the bf16-moment/no-remat stack
        # landed (294 vs 288@64 / 292@80 img/s) — the shared jumbo-MLP
        # weight traffic amortizes over more rows (PERF.md §Round 3)
        batch=72,
        f32_batch=32,
        remat=True,
        remat_policy="dots",
        # framework-leg (bf16) defaults, each A/B'd on chip (PERF.md
        # §ViT-H/14 round 3): bf16 moments free ~4.6 GB of HBM, which lets
        # the model run UN-rematerialized at batch 64 (−13 ms of dots
        # recompute), and the one-hot MXU gather beats the XLA dynamic
        # gather at this scale. The f32 leg keeps the reference-style
        # config above (f32 moments, take gather, dots remat to fit).
        # An UNSET env knob now resolves to these defaults — to sweep a
        # default-on knob OFF use its explicit off spelling:
        # BENCH_MU_DTYPE=float32 BENCH_NU_DTYPE=float32
        # BENCH_GATHER_IMPL=take BENCH_REMAT=1 (spec remat+policy).
        bf16=dict(
            remat=False,
            mu_dtype="bfloat16",
            nu_dtype="bfloat16",
            gather="onehot",
        ),
    ),
}


def _parse_dec_heads(value, dec_dim: int) -> int:
    """Eager validation (leg_config contract: bad knobs die with a clear
    message BEFORE anything is measured): must be an int dividing the
    decoder dim, else head_dim would silently floor and the bench would
    record numbers for a different attention than the config claims."""
    try:
        heads = int(value or 0)
    except (TypeError, ValueError):
        raise SystemExit(
            f"BENCH_DEC_HEADS={value!r} not an integer"
        ) from None
    if heads and dec_dim % heads:
        raise SystemExit(
            f"BENCH_DEC_HEADS={heads} does not divide the decoder dim "
            f"{dec_dim}"
        )
    return heads


def _norm_f32(value):
    """Map the explicit "float32" off-spelling (and unset) to None so the
    master-weights wrapper only engages for real low-precision storage."""
    return None if value in (None, "", "float32") else value


def leg_config(model: str, dtype: str, env=None) -> dict:
    """Resolve the per-leg bench knobs — pure and unit-testable.

    The bf16 leg is the framework at its measured-best TPU config (spec
    "bf16" defaults + BENCH_* env overrides); the f32 leg is the FIXED
    reference-style baseline — env knobs and bf16 defaults never touch it,
    so the two legs stay comparable across sweeps.

    Remat subtlety: an explicit BENCH_REMAT_POLICY also turns remat ON for
    models that default to remat=False — otherwise the override would
    silently no-op (maybe_remat ignores the policy when grad_ckpt is
    false); BENCH_REMAT=0/1 force-overrides both (bf16 moments freed
    enough HBM that no-remat ViT-H/14 fits at the bench batch)."""
    env = os.environ if env is None else env
    spec = MODELS[model]
    framework_leg = dtype == "bfloat16"
    leg = spec.get("bf16", {}) if framework_leg else {}

    def knob(env_name: str, default):
        if framework_leg and env.get(env_name):
            return env[env_name]
        return default

    remat_env = env.get("BENCH_REMAT") if framework_leg else None
    if remat_env:
        if remat_env not in ("0", "1"):
            raise SystemExit(
                f"BENCH_REMAT={remat_env!r} not understood; use 0 or 1"
            )
        grad_ckpt = remat_env == "1"
    else:
        grad_ckpt = leg.get("remat", spec["remat"]) or bool(
            knob("BENCH_REMAT_POLICY", "")
        )
    out = dict(
        grad_ckpt=grad_ckpt,
        remat_policy=knob(
            "BENCH_REMAT_POLICY", spec.get("remat_policy", "none")
        ),
        # masking gather lowering: "take" (XLA gather) vs "onehot" (MXU
        # matmul, concat-free unshuffle) — bit-identical, A/B by profile
        gather_impl=knob("BENCH_GATHER_IMPL", leg.get("gather", "take")),
        # decoder-side remat is its own experiment axis (the decoder runs
        # at head_dim 32 and is un-rematerialized by default)
        dec_remat=env.get("BENCH_DEC_REMAT_POLICY") if framework_leg else None,
        mu_dtype=knob("BENCH_MU_DTYPE", leg.get("mu_dtype")) or None,
        nu_dtype=knob("BENCH_NU_DTYPE", leg.get("nu_dtype")) or None,
        # parameter STORAGE dtype: "bfloat16" stores params bf16 with an f32
        # master copy in the optimizer (train/optim.py with_master_weights) —
        # halves weight-read HBM traffic. "float32" is the explicit off
        # spelling for sweeping a default-on model.
        param_dtype=_norm_f32(knob("BENCH_PARAM_DTYPE", leg.get("param_dtype"))),
        # attention lowering (einsum/flash/ring/auto): at long context the
        # flash kernel avoids materializing the O(S^2) score tensor, which
        # is what OOMs the einsum path first (PERF.md long-context rows)
        attn_impl=knob("BENCH_ATTN_IMPL", "auto"),
        # decoder head-count override (head_dim = 512/heads): heads=8 gives
        # head_dim 64 — the MAE paper's 16h decoder is a recipe choice, and
        # at B scale the d32 decoder attention is the profile's top target
        dec_heads=_parse_dec_heads(
            knob("BENCH_DEC_HEADS", leg.get("dec_heads", 0)),
            spec["dec"]["dim"],
        ),
    )
    if out["attn_impl"] not in ("einsum", "flash", "ring", "auto"):
        # the model's dispatch would silently fall back to einsum and the
        # bench would attribute an einsum measurement to the wrong kernel
        raise SystemExit(
            f"unknown BENCH_ATTN_IMPL {out['attn_impl']!r}; "
            "choose einsum/flash/ring/auto"
        )
    return out


def bench_image_size() -> int:
    """Long-context benching is one knob away: BENCH_IMAGE_SIZE=448 (etc.)
    scales the patch grid. Single parse point — the metric name and the
    workload must agree (the name carries the size so records never mix
    resolutions)."""
    return int(os.environ.get("BENCH_IMAGE_SIZE", "224"))


def build_step(dtype: str, batch_size: int, model: str = "vit_l16"):
    import jax

    from jumbo_mae_tpu_tpu.models import DecoderConfig, MAEPretrainModel, preset
    from jumbo_mae_tpu_tpu.parallel import (
        MeshConfig,
        batch_sharding,
        create_mesh,
    )
    from jumbo_mae_tpu_tpu.train import (
        OptimConfig,
        create_sharded_state,
        make_optimizer,
        make_train_step,
    )

    spec = MODELS[model]
    knobs = leg_config(model, dtype)

    mesh = create_mesh(
        MeshConfig(data=1, fsdp=1), devices=jax.devices()[:1]
    )
    image_size = bench_image_size()
    enc = preset(
        model,
        mask_ratio=0.75,
        labels=None,
        posemb="sincos2d",
        dtype=dtype,
        image_size=image_size,
        grad_ckpt=knobs["grad_ckpt"],
        remat_policy=knobs["remat_policy"],
        gather_impl=knobs["gather_impl"],
        attn_impl=knobs["attn_impl"],
    )
    dec_remat = knobs["dec_remat"]
    dec_spec = dict(spec["dec"])
    if knobs["dec_heads"]:
        dec_spec["heads"] = knobs["dec_heads"]
    dec = DecoderConfig(
        **dec_spec,
        dtype=dtype,
        attn_impl=knobs["attn_impl"],
        grad_ckpt=bool(dec_remat),
        remat_policy=dec_remat or "none",
    )
    module = MAEPretrainModel(enc, dec, norm_pix_loss=True)

    batch = {
        "images": np.random.RandomState(0).randint(
            0, 256, (batch_size, image_size, image_size, 3), dtype=np.uint8
        )
    }
    tx = make_optimizer(
        OptimConfig(
            name="adamw",
            learning_rate=1.5e-4,
            b2=0.95,
            weight_decay=0.05,
            warmup_steps=100,
            training_steps=10_000,
            mu_dtype=knobs["mu_dtype"],
            nu_dtype=knobs["nu_dtype"],
            param_dtype=knobs["param_dtype"],
        ),
        global_batch_size=batch_size,
    )
    state, sharding = create_sharded_state(
        module, tx, batch, mesh, mode="pretrain",
        param_dtype=knobs["param_dtype"],
    )
    step = make_train_step(mesh, sharding, mode="pretrain")
    # Stage the batch on device once: training overlaps host→device copies
    # with compute (data/loader.py prefetch_to_device), so steady-state
    # throughput is device-bound — that is what this measures.
    batch = jax.device_put(batch, batch_sharding(mesh))

    # analytic step FLOPs → the 100%-MFU step-time floor for the timing
    # plausibility guard (a real measurement can never beat the chip's peak).
    # Unknown accelerators disable the guard (floor 0) rather than inherit a
    # fallback peak that a faster chip could legitimately beat.
    from jumbo_mae_tpu_tpu.utils.mfu import detect_peak_tflops, pretrain_flops_per_image

    peak = detect_peak_tflops(default=0.0)
    flops_per_step = pretrain_flops_per_image(enc, dec) * batch_size
    floor_ms = 0.0 if peak <= 0 else flops_per_step / (peak * 1e12) * 1e3
    return step, state, batch, floor_ms


def time_steps(
    step,
    state,
    batch,
    *,
    warmup: int,
    iters: int,
    rounds: int = 3,
    min_plausible_ms: float = 0.0,
) -> float:
    """Best-of-``rounds`` mean step time over ``iters`` chained async steps.

    Each round dispatches ``iters`` steps back-to-back with ONE final
    block_until_ready (steady-state pattern; per-step sync would add the
    ~130 ms tunnel round-trip). The min across rounds rejects interference
    noise on the shared remote chip — both bench legs get identical
    treatment so the ratio is defensible.

    ``min_plausible_ms`` guards against silently corrupt rounds: over the
    remote tunnel, block_until_ready has been observed (rarely) to return
    before the dispatched programs finished, yielding step times that imply
    more than the chip's peak FLOP/s. Any round below the floor — derived
    from analytic workload FLOPs at 100% MFU, so a legitimate measurement
    can never hit it — is discarded and re-run, after a full data fetch
    forces real completion."""
    import jax

    for _ in range(warmup):
        state, metrics = step(state, batch)
    jax.block_until_ready(metrics["loss"])
    best = float("inf")
    done = retries = 0
    while done < rounds and retries < 3 * rounds:
        t0 = time.perf_counter()
        for _ in range(iters):
            state, metrics = step(state, batch)
        jax.block_until_ready(metrics["loss"])
        dt = (time.perf_counter() - t0) / iters
        loss = float(metrics["loss"])  # full fetch: forces real completion
        if not np.isfinite(loss):
            raise RuntimeError(f"bench produced non-finite loss {loss}")
        if dt * 1e3 < min_plausible_ms:
            retries += 1
            continue
        best = min(best, dt)
        done += 1
    if done == 0:
        raise RuntimeError(
            f"every bench round measured below the {min_plausible_ms:.1f} ms "
            "plausibility floor — timing is broken, not fast"
        )
    return best


# substrings of genuinely transient tunnel faults: a remote compile served
# over the tunnel can drop mid-body (observed live: "remote_compile: read
# body: response body closed before all bytes were read"). Deliberately
# narrow — RESOURCE_EXHAUSTED (OOM) and shape errors must fail fast.
_LEG_TRANSIENT = (
    # the connection-drop signature specifically — a bare "remote_compile"
    # would also match PERMANENT compile errors reported through the same
    # endpoint URL and retry them pointlessly
    "read body",
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
)


# XLA cost analysis per measured leg ("<dtype>-b<batch>" → cost dict),
# recorded by _measure_leg as a side table: the ledger row wants the costs,
# but _measure_leg's float return is load-bearing for its callers/tests.
_LEG_COSTS: dict = {}


def _record_leg_cost(key: str, step, batch_size: int) -> None:
    """Best-effort: read XLA's cost analysis off the leg's train-step
    executable (the AOT dispatch in train/steps exposes it — no recompile)."""
    try:
        from jumbo_mae_tpu_tpu.obs.costmodel import cost_asdict, extract_cost

        execs = getattr(step, "executables", None) or {}
        for ex in execs.values():
            cost = extract_cost(ex, "train_step")
            if cost is not None:
                _LEG_COSTS[key] = cost_asdict(cost) | {"batch": batch_size}
            break
    except Exception:  # noqa: BLE001 — observability must not fail a leg
        pass


def _measure_leg(dtype: str, batch_size: int, model: str, iters: int) -> float:
    """Build + time one bench leg, retrying transient tunnel faults.

    One retry on a fresh build costs minutes; an error artifact costs the
    round its perf evidence (a live f32 leg died to exactly this after the
    bf16 leg had already measured clean)."""
    attempts = max(0, int(os.environ.get("BENCH_LEG_RETRIES", "2"))) + 1
    for i in range(attempts):
        step = state = batch = None
        try:
            step, state, batch, floor = build_step(dtype, batch_size, model)
            dt = time_steps(
                step,
                state,
                batch,
                warmup=3,
                iters=iters,
                min_plausible_ms=floor,
            )
            _record_leg_cost(f"{dtype}-b{batch_size}", step, batch_size)
            return dt
        except Exception as exc:  # noqa: BLE001 — classify then re-raise
            # drop the failed attempt's device buffers BEFORE rebuilding —
            # otherwise the retry allocates a second full param/opt/batch
            # set next to the dead one and OOMs the leg it came to save
            step = state = batch = None
            msg = str(exc)
            if i + 1 >= attempts or not any(
                t in msg for t in _LEG_TRANSIENT
            ):
                raise
            print(
                f"bench: transient fault on {dtype} leg (attempt {i + 1}): "
                f"{msg.splitlines()[0][:160]}; retrying",
                file=sys.stderr,
                flush=True,
            )
            time.sleep(10)
    raise AssertionError("unreachable")


def _run_bench() -> dict:
    model = os.environ.get("BENCH_MODEL", "vit_l16")
    if model not in MODELS:
        raise SystemExit(
            f"unknown BENCH_MODEL {model!r}; choose from {sorted(MODELS)}"
        )
    batch_size = int(os.environ.get("BENCH_BATCH", str(MODELS[model]["batch"])))
    iters = int(os.environ.get("BENCH_ITERS", "10"))
    size = bench_image_size()
    _partial["metric"] = f"mae_{model}_{size}_pretrain_imgs_per_sec_per_chip"

    dt = _measure_leg("bfloat16", batch_size, model, iters)
    imgs_per_sec = batch_size / dt
    _partial["value"] = round(imgs_per_sec, 2)
    _partial["ms_step_bf16"] = round(dt * 1e3, 2)

    result = {
        "metric": _partial["metric"],
        "value": _partial["value"],
        "unit": "imgs/sec/chip",
        "vs_baseline": None,
        "ms_step_bf16": _partial["ms_step_bf16"],
    }
    if not os.environ.get("BENCH_SKIP_BASELINE"):
        # The baseline leg (reference-style fp32 compute, same workload)
        # gets IDENTICAL warmup/iters/rounds so the ratio is two equally
        # converged measurements, not a converged one over a noisy one.
        # f32 doubles activation memory; models that need a smaller f32
        # batch declare it, and the ratio compares per-image throughput.
        # never larger than the bf16 leg's batch: a user-shrunk BENCH_BATCH
        # must shrink the f32 leg too (its declared batch is sized for the
        # default config's memory envelope)
        batch_f32 = int(
            os.environ.get(
                "BENCH_F32_BATCH",
                str(min(MODELS[model].get("f32_batch", batch_size), batch_size)),
            )
        )
        dt_f32 = _measure_leg("float32", batch_f32, model, iters)
        result["vs_baseline"] = round(imgs_per_sec / (batch_f32 / dt_f32), 3)
        result["ms_step_f32"] = round(dt_f32 * 1e3, 2)
        _partial["vs_baseline"] = result["vs_baseline"]
        if batch_f32 != batch_size:
            # The headline ratio folds batch-size efficiency into the config
            # win. Time a framework leg AT the f32 batch too, so the artifact
            # also carries a framework-config vs reference-style ratio at
            # equal batch (the framework leg keeps its tuned per-model knobs
            # — gather/remat/moment dtypes — so this is NOT dtype-only).
            result["f32_batch"] = batch_f32
            dt_eq = _measure_leg("bfloat16", batch_f32, model, iters)
            result["vs_baseline_equal_batch"] = round(dt_f32 / dt_eq, 3)
    _append_ledger(result, batch_size)
    return result


def _append_ledger(result: dict, batch_size: int) -> None:
    """Land this round in BENCH_HISTORY.jsonl (``obs/perfledger``): legs,
    the XLA-extracted bf16-leg cost, and its roofline prediction. Best
    effort — the one-JSON-line stdout contract is unaffected either way."""
    try:
        from jumbo_mae_tpu_tpu.obs.perfledger import (
            append_row,
            make_row,
            resolve_history_path,
        )

        path = resolve_history_path()
        if path is None:
            return
        legs = {
            k: result[k]
            for k in (
                "value",
                "ms_step_bf16",
                "ms_step_f32",
                "vs_baseline",
                "vs_baseline_equal_batch",
            )
            if result.get(k) is not None
        }
        prediction = None
        cost = _LEG_COSTS.get(f"bfloat16-b{batch_size}")
        if cost:
            from jumbo_mae_tpu_tpu.obs.perfmodel import (
                detect_chip,
                prediction_asdict,
                roofline,
            )

            pred = roofline(
                cost["flops"],
                cost["bytes_accessed"],
                detect_chip(),
                batch=cost.get("batch"),
                peak_hbm_bytes=cost.get("peak_bytes", 0.0),
            )
            prediction = prediction_asdict(pred)
        row = make_row(
            bench="train",
            metric=result["metric"],
            legs=legs,
            prediction=prediction,
            extra={"unit": result.get("unit"), "cost": cost},
        )
        if append_row(path, row):
            print(f"bench: ledger row -> {path}", file=sys.stderr)
    except Exception as e:  # noqa: BLE001 — the ledger must not fail a bench
        print(f"bench: ledger append failed: {e}", file=sys.stderr)


def main():
    _start_watchdog(float(os.environ.get("BENCH_WATCHDOG_SECS", 1500)))
    try:
        acquire_backend()
        result = _run_bench()
    except BaseException as e:  # noqa: BLE001 — the artifact must be JSON either way
        import traceback

        traceback.print_exc(file=sys.stderr)  # full evidence on stderr
        _emit_error(f"{type(e).__name__}: {e}")  # machine-readable on stdout
        return 1
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
